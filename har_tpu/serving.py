"""Real-time streaming inference: sliding-window HAR classification.

The reference has no serving story — it scores one static test DataFrame
in batch (`Main/main.py:122-130`) and its models die with the process
(no persistence, SURVEY §5.4).  A deployed activity-recognition system
consumes a *live* 20 Hz accelerometer stream; this module is the
TPU-native serving path for that gap:

  ``StreamingClassifier``  — ring-buffer sliding windows over an
    incremental sample stream; one fixed-shape compiled predict per hop
    (XLA traces a single ``(1, window, C)`` program once, every later
    hop reuses it — no retracing on the hot path), plus probability
    smoothing (EMA or k-window majority vote), because single-window
    flips are the dominant error mode of deployed HAR.

  ``classify_session``  — offline replay of a recorded stream at full
    batch throughput: strided window view → one batched ``transform``.
    Bit-identical to streaming the same samples with smoothing off
    (tested: tests/test_serving.py).

Fleet scale — thousands of concurrent sessions multiplexed onto the
same compiled predict — lives in ``har_tpu.serve`` (``FleetServer``); it
composes the shared building blocks defined here (``_WindowAssembler``,
``_Smoother``, ``device_predict_fn``), which is what makes its events
bit-identical to N independent ``StreamingClassifier`` runs.

TPU design notes:
  - Static shapes everywhere: window length, hop and channel count are
    construction-time constants; ``push`` never changes a traced shape.
  - The ring buffer lives on host (numpy).  At 20 Hz the device round
    trip per hop IS the latency floor; a ``(window, 3)`` f32 window is
    ~2.4 KB — transfer-irrelevant.  What matters is never re-tracing
    and never re-compiling, which fixed shapes guarantee.
  - Catch-up bursts (a transport hiccup delivers seconds of samples at
    once) are scored in BATCHED predicts — one dispatch per 256
    completed windows, padded to power-of-two batch shapes so at most a
    handful of programs ever compile — instead of one ~hundreds-of-ms
    tunnel round-trip per hop; smoothing still runs sequentially, so
    events are identical to hop-by-hop pushes (test-pinned).  For bulk
    re-scoring of recorded sessions ``classify_session`` remains the
    zero-copy throughput path.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class StreamEvent:
    """One classification emitted at a hop boundary."""

    t_index: int  # stream sample index of the window END (exclusive)
    label: int  # smoothed class decision
    raw_label: int  # this window's own argmax (pre-smoothing)
    probability: np.ndarray  # (C,) decision distribution: EMA-smoothed
    #   probs ("ema"), trailing vote fractions ("vote"), or the window's
    #   own probs ("none"); probability[label] is the decision confidence
    latency_ms: float  # wall-clock of the predict for this window
    drift: bool = False  # input stream out of training distribution
    #   (only when a monitoring.DriftMonitor is attached; see
    #   StreamingClassifier(monitor=...))
    device_ms: float | None = None  # calibrated DEVICE share of
    #   latency_ms for this window's dispatch (None before a device
    #   calibration exists); latency_ms - device_ms is host/transfer/
    #   tunnel overhead — what lets a serving consumer attribute a p99
    #   spike to the tunnel vs the chip per event


def _percentile(values: Sequence[float], q: float) -> float:
    return float(np.percentile(np.asarray(values, np.float64), q))


def finite_rows(
    samples: np.ndarray, max_abs: float | None = 1e6
) -> tuple[np.ndarray, int]:
    """THE ingest guard shared by StreamingClassifier.push and
    FleetServer.push: drop sample rows that are non-finite (NaN/Inf) or
    wildly out of range (any |value| > max_abs; None disables the range
    check).  Returns ``(clean_rows, n_rejected)``.

    One poisoned row would otherwise ride a window into the compiled
    predict and NaN-poison the whole micro-batch — on the fleet path
    that is 256 sessions' windows dying to one broken sensor.  Rejection
    is per ROW and silent by design (counted, never raised): the
    serving loop must keep serving the finite samples it does get.

    ONE reduction over the pushed block classifies all three failure
    modes: the per-row abs-max is NaN for any NaN entry, +inf for any
    ±Inf entry, and > max_abs for an out-of-range one — so a single
    ``m <= max_abs`` comparison (NaN/Inf both compare False against any
    finite bound) replaces the separate isfinite + range passes.  The
    equivalence with the two-pass guard is test-pinned on poisoned
    streams.

    Fast path first: the CHUNK-level scalar abs-max answers the common
    all-clean case in one reduction with no per-row bookkeeping at all
    (a NaN/Inf/out-of-range entry makes the scalar fail its bound
    check, falling through to the row-classifying path) — at fleet
    ingest rates the guard runs per delivery chunk for thousands of
    sessions per round, and the row machinery was measurably on the
    serving hot path.
    """
    if samples.size == 0:
        return samples, 0
    # no errstate on the fast path: abs/max propagate NaN silently and
    # the scalar comparison below is plain Python — only the per-row
    # classification needs the invalid-compare guard
    chunk_max = float(np.abs(samples).max())
    clean = (
        chunk_max <= max_abs  # NaN/Inf compare False: fall through
        if max_abs is not None
        else np.isfinite(chunk_max)
    )
    if clean:
        return samples, 0
    with np.errstate(invalid="ignore"):
        m = np.abs(samples).max(axis=-1)
        if max_abs is not None:
            good = m <= max_abs
        else:
            # range check disabled: only NaN/Inf rows are rejected
            good = np.isfinite(m)
    n_bad = int(len(good) - good.sum())
    if n_bad:
        return samples[good], n_bad
    return samples, 0


def pad_pow2(windows: np.ndarray) -> np.ndarray:
    """Pad a ``(k, ...)`` batch to the next power-of-two rows by
    repeating the last row — THE batch-shape policy of every scoring
    path (streaming catch-up bursts, fleet dispatches, shadow mirrors),
    so at most log2(max_batch)+1 programs ever compile and no path can
    silently diverge from the others' compiled-shape budget."""
    k = len(windows)
    pad_k = 1 << (k - 1).bit_length()
    if pad_k == k:
        return windows
    return np.concatenate(
        [windows, np.repeat(windows[-1:], pad_k - k, axis=0)]
    )


def pad_shard(windows: np.ndarray, shards: int = 1) -> np.ndarray:
    """Pad a ``(k, ...)`` batch to ``shards × pow2(ceil(k / shards))``
    rows by repeating the last row — the batch-shape policy of the
    mesh-sharded dispatch path (har_tpu.serve.dispatch).  The leading
    dim always divides the shard count (a NamedSharding over the batch
    axis needs it), and per device count the padded sizes still walk a
    power-of-two ladder, so at most log2(max_batch)+1 programs compile
    per device shape — the same compiled-program budget as the
    single-device ``pad_pow2`` policy (``shards=1`` is exactly it)."""
    k = len(windows)
    per = -(-k // shards)  # ceil
    pad_k = shards * (1 << (per - 1).bit_length())
    if pad_k == k:
        return windows
    return np.concatenate(
        [windows, np.repeat(windows[-1:], pad_k - k, axis=0)]
    )


def device_predict_fn(model):
    """The compiled device predict behind any serving wrapper chain.

    Unwraps NeuralClassifierModel's ``.inner`` and
    TemperatureScaledModel's ``.model`` (the device program is the same
    base forward either way — temperature/scaler are host-side); an
    ExportedPredictor (StableHLO artifact) is reached via its exported
    ``device_call``.  Shared by ``StreamingClassifier.device_latency_ms``
    and the fleet engine's dispatch calibration so both report the same
    device-vs-host decomposition.  Raises ValueError for models without
    a jitted predict (trees, MLlib replicas, host-side stubs).
    """
    inner = model
    for _ in range(4):
        if hasattr(inner, "_predict") and hasattr(inner, "params"):
            return lambda x: inner._predict(inner.params, x)
        if hasattr(inner, "device_call"):
            return inner.device_call  # ExportedPredictor
        nxt = getattr(inner, "inner", None)
        if nxt is None:
            nxt = getattr(inner, "model", None)
        if nxt is None:
            break
        inner = nxt
    raise ValueError(
        "device timing needs a NeuralModel-backed or exported-"
        f"artifact classifier (got {type(model).__name__}); "
        "e2e latency stats are still available"
    )


def measure_device_latency(
    model, *, window: int, channels: int, batch: int = 1, iters: int = 16
) -> dict:
    """Device dispatch+compute p50 for one ``(batch, window, channels)``
    predict: device-resident input, ``block_until_ready``, no host
    staging, no scaler, no result fetch.  See
    ``StreamingClassifier.device_latency_ms`` for the interpretation."""
    fn = device_predict_fn(model)
    import jax.numpy as jnp

    x = jnp.zeros((batch, window, channels), jnp.float32)
    fn(x).block_until_ready()  # warm
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn(x).block_until_ready()
        times.append((time.perf_counter() - t0) * 1e3)
    return {
        "batch": batch,
        "iters": iters,
        "p50_ms": round(_percentile(times, 50), 3),
        "min_ms": round(min(times), 3),
    }


class _WindowAssembler:
    """Ring-buffer sliding-window ingestion over an incremental stream.

    One implementation shared by the single-stream StreamingClassifier
    and the fleet engine's per-session state (har_tpu.serve): a
    multiplexed session therefore produces bit-identical window
    snapshots — and drift verdicts, which are chunk-cadence-dependent
    EWMAs — to a standalone classifier fed the same delivery chunks.
    """

    __slots__ = (
        "window", "hop", "channels", "monitor", "drift_report",
        "_ring", "_n_seen", "_next_emit",
    )

    def __init__(
        self, window: int, hop: int, channels: int, monitor=None,
        ring: np.ndarray | None = None,
    ):
        self.window = window
        self.hop = hop
        self.channels = channels
        self.monitor = monitor
        self.drift_report = None
        # ``ring`` — optional externally-owned storage (must arrive
        # zeroed): the fleet engine's session arena passes one row of
        # its contiguous ring block here (har_tpu.serve.arena), so ten
        # thousand sessions share one allocation instead of ten
        # thousand scattered ones.  The assembler's logic is identical
        # either way — which is the bit-identity argument for the
        # structure-of-arrays host plane.
        self._ring = (
            np.zeros((window, channels), np.float32)
            if ring is None
            else ring
        )
        self._n_seen = 0
        self._next_emit = window

    @property
    def n_seen(self) -> int:
        return self._n_seen

    def consume(
        self, samples: np.ndarray, sink=None
    ) -> list[tuple[int, object, bool]]:
        """Absorb ``(n, channels)`` samples; return the ``(t_index,
        window_snapshot, drift)`` tuple for every hop boundary they
        complete (scoring is the caller's job).

        ``sink`` — optional staging target with ``put(window) -> token``
        (and optionally ``put_block(windows) -> [token]``): each
        completed window is written ONCE into the sink's storage and the
        returned tuples carry the token instead of a fresh array copy.
        The fleet engine passes its contiguous staging arena here
        (har_tpu.serve.dispatch.StagingArena), so batch assembly later
        is a gather out of one preallocated block instead of a stack of
        per-window allocations.

        When no drift monitor is attached and a chunk completes several
        windows at once (catch-up bursts, offline replay), the window
        snapshots are produced VECTORIZED: one strided view over
        ``ring ++ samples`` and one block copy, instead of a ring roll +
        copy per hop boundary.  The produced windows are byte-identical
        to the sequential path's — same stream rows, same dtype — which
        the equivalence suite pins by construction (chunking never
        changes events).
        """
        if (
            not isinstance(samples, np.ndarray)
            or samples.ndim != 2
            or samples.dtype != np.float32
        ):
            # already-clean (n, C) f32 input (the fleet engine's push
            # normalized it) skips the per-chunk conversion churn — at
            # 20 Hz × thousands of sessions these two calls were
            # measurably on the ingest hot path
            samples = np.atleast_2d(np.asarray(samples, np.float32))
        if samples.shape[-1] != self.channels:
            raise ValueError(
                f"expected (n, {self.channels}) samples, got "
                f"{samples.shape}"
            )
        pending: list[tuple[int, object, bool]] = []
        pos = 0
        n = len(samples)
        if self.monitor is None and n:
            # boundaries this chunk completes: next_emit, next_emit+hop,
            # ... <= n_seen + n (drift is False for all of them — no
            # monitor — so per-boundary sequencing has nothing to order)
            nb = (self._n_seen + n - self._next_emit) // self.hop + 1
            if nb >= 2:
                return self._consume_vectorized(samples, nb, sink)
        while pos < n:
            # advance at most to the next emission boundary, so no
            # boundary inside a large chunk is skipped
            take = min(self._next_emit - self._n_seen, n - pos)
            chunk = samples[pos : pos + take]
            if self.monitor is not None and take:
                # per consumed chunk, NOT per push: a whole recording
                # pushed at once must step the monitor at the same
                # cadence live streaming would, or the debounce could
                # never fire and events would all share one end-of-
                # recording verdict
                self.drift_report = self.monitor.update(chunk)
            # roll the ring by `take`: cheap at stream chunk sizes, and
            # keeps the window contiguous for the device transfer
            if take >= self.window:
                self._ring[:] = chunk[-self.window :]
            else:
                self._ring[: self.window - take] = self._ring[take:]
                self._ring[self.window - take :] = chunk
            self._n_seen += take
            pos += take
            if self._n_seen == self._next_emit:
                pending.append(
                    (
                        self._n_seen,
                        (
                            self._ring.copy()
                            if sink is None
                            else sink.put(self._ring)
                        ),
                        bool(
                            self.drift_report is not None
                            and self.drift_report.drifting
                        ),
                    )
                )
                self._next_emit += self.hop
        return pending

    def _consume_vectorized(
        self, samples: np.ndarray, nb: int, sink
    ) -> list[tuple[int, object, bool]]:
        """Multi-boundary fast path (no monitor attached): one strided
        view over ``ring ++ samples`` yields every completed window, one
        block copy stages them all.  State updates collapse to closed
        forms — the final ring is the last ``window`` stream rows either
        way."""
        n = len(samples)
        buf = np.ascontiguousarray(np.concatenate([self._ring, samples]))
        # buf[i] is stream row (n_seen - window + i); the window ending
        # at boundary b spans buf[b - n_seen : b - n_seen + window]
        first = self._next_emit - self._n_seen
        s0, s1 = buf.strides
        view = np.lib.stride_tricks.as_strided(
            buf[first:],
            shape=(nb, self.window, self.channels),
            strides=(self.hop * s0, s0, s1),
            writeable=False,
        )
        if sink is None:
            snaps = list(np.ascontiguousarray(view))
        elif hasattr(sink, "put_block"):
            snaps = sink.put_block(view)
        else:
            snaps = [sink.put(w) for w in view]
        t0 = self._next_emit
        pending = [
            (t0 + i * self.hop, snap, False)
            for i, snap in enumerate(snaps)
        ]
        self._next_emit = t0 + nb * self.hop
        self._n_seen += n
        if n >= self.window:
            self._ring[:] = samples[-self.window :]
        else:
            self._ring[: self.window - n] = self._ring[n:]
            self._ring[self.window - n :] = samples
        return pending


class _Smoother:
    """Sequential decision smoothing over per-window probabilities.

    The one implementation of the EMA / majority-vote / passthrough
    decision rule, shared by StreamingClassifier and the fleet engine's
    per-session state — fleet-multiplexed smoothing is bit-identical to
    standalone smoothing by construction, not by parallel maintenance.
    """

    __slots__ = ("smoothing", "ema_alpha", "_ema", "_votes")

    def __init__(self, smoothing: str, ema_alpha: float, vote_depth: int):
        self.smoothing = smoothing
        self.ema_alpha = ema_alpha
        self._ema: np.ndarray | None = None
        self._votes: deque[int] = deque(maxlen=vote_depth)

    def step(self, probs: np.ndarray) -> tuple[int, int, np.ndarray]:
        """Absorb one window's ``(C,)`` probabilities (in emission
        order); return ``(label, raw_label, decision_probs)``."""
        return self._step_raw(int(probs.argmax()), probs)

    def _step_raw(
        self, raw_label: int, probs: np.ndarray
    ) -> tuple[int, int, np.ndarray]:
        """``step`` with the raw argmax precomputed — ``update_many``
        vectorizes the argmax over a session's whole block (one
        reduction instead of one per row) and feeds the recurrence
        through here; the decision logic is byte-for-byte ``step``'s."""
        if self.smoothing == "ema":
            self._ema = (
                probs
                if self._ema is None
                else self.ema_alpha * probs
                + (1.0 - self.ema_alpha) * self._ema
            )
            smoothed = self._ema
            label = int(smoothed.argmax())
        elif self.smoothing == "vote":
            votes = self._votes
            votes.append(raw_label)
            # integer vote counting in plain Python: the deque holds at
            # most vote_depth small ints, and per-window np.bincount/
            # max/array churn was measurably on the fleet retire hot
            # path.  Integer arithmetic is exact, so the counts — and
            # the float64 division below — are bit-identical to the
            # previous numpy formulation (test-pinned vs step-by-step).
            # Width mirrors bincount(minlength=C): a stale vote from
            # before a swap to a NARROWER model still counts instead of
            # crashing the retire loop with an IndexError.
            width = probs.shape[0]
            for v in votes:
                if v >= width:
                    width = v + 1
            counts = [0] * width
            for v in votes:
                counts[v] += 1
            best = max(counts)
            # ties break toward the newest label that achieves the max
            label = next(
                v for v in reversed(votes) if counts[v] == best
            )
            # the event's probability must describe the DECISION, so in
            # vote mode it is the trailing vote distribution (the raw
            # window's own distribution stays reachable via raw_label);
            # probability[label] is then the vote confidence
            smoothed = np.asarray(counts, np.float64) / len(votes)
        else:
            smoothed = probs
            label = raw_label
        return label, raw_label, smoothed

    def update_many(
        self, probs: np.ndarray
    ) -> list[tuple[int, int, np.ndarray]]:
        """Absorb a ``(m, C)`` block of one session's per-window
        probabilities IN EMISSION ORDER; returns ``step``'s tuple per
        row.  The fleet engine's retire path calls this once per
        (session, batch) instead of ``step`` per row: the stateless
        passthrough mode vectorizes outright (one argmax over the
        block), while the stateful EMA/vote modes run the SAME
        sequential recurrence — vectorizing an EMA would re-associate
        the float chain and break the bit-identity contract with a
        standalone classifier."""
        if self.smoothing == "none":
            raws = probs.argmax(axis=1)
            return [
                (int(r), int(r), p) for r, p in zip(raws, probs)
            ]
        # stateful modes: the raw argmax is still one vectorized
        # reduction over the block; only the recurrence runs per row
        raws = probs.argmax(axis=1)
        return [
            self._step_raw(int(r), p) for r, p in zip(raws, probs)
        ]


class StreamingClassifier:
    """Sliding-window online classifier over an incremental stream.

    Parameters
    ----------
    model:
        Any fitted model with ``transform(x) -> Predictions`` over
        ``(n, window, channels)`` raw windows — a
        ``NeuralClassifierModel`` (scaler applied inside) or a bare
        ``NeuralModel``.
    window, hop:
        Window length and emission stride in samples.  The WISDM
        protocol is 200-sample (10 s @ 20 Hz) windows; ``hop=20`` emits
        one decision per second.
    smoothing:
        ``"ema"`` — exponential moving average over class probabilities
        (``ema_alpha`` = weight of the newest window);
        ``"vote"`` — majority vote over the last ``vote_depth`` raw
        labels (ties break toward the newest);
        ``"none"`` — every event reports its own window verbatim.
    """

    def __init__(
        self,
        model,
        *,
        window: int = 200,
        hop: int = 20,
        channels: int = 3,
        smoothing: str = "ema",
        ema_alpha: float = 0.4,
        vote_depth: int = 5,
        class_names: Sequence[str] | None = None,
        monitor=None,
        max_abs_sample: float | None = 1e6,
    ):
        if window <= 0 or hop <= 0:
            raise ValueError("window and hop must be positive")
        if smoothing not in ("ema", "vote", "none"):
            raise ValueError(f"unknown smoothing {smoothing!r}")
        if smoothing == "ema" and not (0.0 < ema_alpha <= 1.0):
            raise ValueError("ema_alpha must be in (0, 1]")
        if smoothing == "vote" and vote_depth < 1:
            raise ValueError("vote_depth must be >= 1")
        self.model = model
        self.window = int(window)
        self.hop = int(hop)
        self.channels = int(channels)
        self.smoothing = smoothing
        self.ema_alpha = float(ema_alpha)
        self.vote_depth = int(vote_depth)
        self.class_names = list(class_names) if class_names else None
        # optional monitoring.DriftMonitor: fed every pushed sample;
        # events carry drift=True while the stream is out of the
        # training distribution
        self.monitor = monitor
        # ingest guard (finite_rows): rejected rows are counted here,
        # never raised — the same per-session guard FleetServer applies,
        # so a multiplexed session stays bit-identical to this class
        self.max_abs_sample = max_abs_sample
        self.rejected_samples = 0
        self.reset()

    @classmethod
    def from_checkpoint(cls, path: str, **kwargs) -> "StreamingClassifier":
        """Serve a saved neural checkpoint (har_tpu.checkpoint layout).

        Window geometry defaults to the checkpoint's recorded
        ``input_shape`` and a conflicting explicit ``window``/``channels``
        is rejected: a pooled CNN runs at any window length, so a
        mismatch would not error — it would silently emit predictions on
        a distribution the params never saw.  ``None`` kwargs mean
        "unset" (use the checkpoint's geometry).
        """
        from har_tpu.checkpoint import load_model, load_model_meta

        kwargs = {k: v for k, v in kwargs.items() if v is not None}
        try:
            shape = load_model_meta(path).get("input_shape")
        except OSError:
            shape = None
        if shape and len(shape) == 2:
            trained = {"window": int(shape[0]), "channels": int(shape[1])}
            for name, value in trained.items():
                asked = kwargs.get(name)
                if asked is not None and asked != value:
                    raise ValueError(
                        f"checkpoint records input_shape={shape} "
                        f"({name}={value}); serving with {name}={asked} "
                        "would feed the model windows it was never "
                        "trained on"
                    )
                kwargs.setdefault(name, value)
        model = load_model(path)
        if kwargs.get("monitor") == "auto":
            # drift detection against the checkpoint's own training
            # statistics (the scaler's mean/std)
            from har_tpu.monitoring import DriftMonitor

            if getattr(model, "scaler", None) is None:
                raise ValueError(
                    "this checkpoint records no training statistics "
                    "(model trained with standardize=False), so "
                    "monitor='auto' has nothing to compare against; "
                    "build DriftMonitor.from_windows(training_windows) "
                    "and pass it as monitor= instead"
                )
            kwargs["monitor"] = DriftMonitor.from_model(model)
        return cls(model, **kwargs)

    def reset(self) -> None:
        """Drop buffered samples and smoothing state (stream restart)."""
        # ring buffer of the newest `window` samples; decisions fire at
        # sample counts window, window+hop, window+2*hop, ... — shared
        # with the fleet engine's per-session state (har_tpu.serve)
        self._asm = _WindowAssembler(
            self.window, self.hop, self.channels,
            monitor=getattr(self, "monitor", None),
        )
        self._smoother = _Smoother(
            self.smoothing, self.ema_alpha, self.vote_depth
        )
        # bounded: a deployed 20 Hz session runs for days (the paper's
        # elderly-monitoring use case) — percentiles over a trailing
        # window keep the stats current AND the memory constant; 4096
        # dispatches ≈ 68 min of hop-per-second serving
        self._latencies: deque[float] = deque(maxlen=4096)
        # device-only calibration results keyed by batch size; survives
        # reset() would be wrong — a restarted stream may follow a
        # checkpoint swap, so measurements restart with the session
        self._device_ms: dict[int, dict] = {}
        if getattr(self, "monitor", None) is not None:
            self.monitor.reset()
        # the first predict EVER pays compilation; a reset() on a warm
        # classifier starts a session whose first sample is already fast
        self._session_starts_cold = not getattr(
            self, "_ever_predicted", False
        )

    # ---------------------------------------------------------- streaming

    def push(self, samples: np.ndarray) -> list[StreamEvent]:
        """Feed ``(n, channels)`` samples; return events for every hop
        boundary they complete.  Chunking is irrelevant: pushing a
        recording sample-by-sample or all at once yields identical
        events (the test suite pins this)."""
        # Pass 0: the ingest guard — a NaN/Inf or out-of-range row must
        # never reach the compiled predict (it would poison the whole
        # window, and on the fleet path the whole micro-batch)
        samples = np.atleast_2d(np.asarray(samples, np.float32))
        samples, n_bad = finite_rows(samples, self.max_abs_sample)
        self.rejected_samples += n_bad
        # Pass 1: consume samples, collecting the window snapshot (and
        # the drift verdict as of that moment) at every boundary — the
        # shared _WindowAssembler, so the fleet engine's sessions see
        # identical snapshots for identical delivery chunks.
        pending = self._asm.consume(samples)
        # Pass 2: score every completed window with as few dispatches as
        # possible — catch-up bursts (and offline replay through push)
        # pay one batched predict per _MAX_BATCH windows, not one
        # dispatch round-trip per hop (~200 ms each through a remote
        # tunnel).  Smoothing then runs sequentially over the rows, so
        # events are identical to hop-by-hop pushes.
        events: list[StreamEvent] = []
        for start in range(0, len(pending), self._MAX_BATCH):
            block = pending[start : start + self._MAX_BATCH]
            probs_block, lat_share = self._score(
                np.stack([w for _, w, _ in block])
            )
            for (t_index, _, drift), probs in zip(block, probs_block):
                events.append(
                    self._make_event(t_index, probs, lat_share, drift)
                )
        return events

    # windows scored per predict call; bursts beyond this loop.  Batch
    # shapes are padded to powers of two so at most log2(_MAX_BATCH)+1
    # distinct shapes ever compile.
    _MAX_BATCH = 256

    def _score(self, windows: np.ndarray) -> tuple[np.ndarray, float]:
        """(probs (k, C), per-window latency share in ms) — ONE timed
        model.transform for the whole block."""
        k = len(windows)
        windows = pad_pow2(windows)
        t0 = time.perf_counter()
        preds = self.model.transform(windows)
        latency_ms = (time.perf_counter() - t0) * 1e3
        self._latencies.append(latency_ms)
        self._ever_predicted = True
        return (
            np.asarray(preds.probability[:k], np.float64),
            latency_ms / k,
        )

    def _make_event(
        self, t_index: int, probs: np.ndarray, latency_ms: float,
        drift: bool,
    ) -> StreamEvent:
        label, raw_label, smoothed = self._smoother.step(probs)
        return StreamEvent(
            t_index=t_index,
            label=label,
            raw_label=raw_label,
            probability=smoothed.copy(),
            latency_ms=latency_ms,
            drift=drift,
        )

    def replay(
        self, samples: np.ndarray, *, calibrate: bool = True
    ) -> list[StreamEvent]:
        """Replay a recording at the LIVE cadence: hop-sized pushes, one
        dispatch per hop, so ``latency_stats()`` afterwards is the
        per-hop serving floor (a single whole-recording ``push`` batches
        into one dispatch and measures replay throughput instead — that
        path is ``classify_session``).  With ``calibrate``, runs the
        batch-1 ``device_latency_ms`` measurement afterwards (skipped
        silently for models without a jitted predict) so the stats also
        separate device compute from host/transfer/tunnel overhead.
        Events are identical to any other chunking of the same samples.
        """
        samples = np.atleast_2d(np.asarray(samples, np.float32))
        events: list[StreamEvent] = []
        for start in range(0, len(samples), self.hop):
            events.extend(self.push(samples[start : start + self.hop]))
        if calibrate:
            try:
                self.device_latency_ms(batch=1)
            except ValueError:
                pass
        return events

    # ---------------------------------------------------------- reporting

    def device_latency_ms(self, batch: int = 1, iters: int = 16) -> dict:
        """Measure DEVICE execution time for the compiled predict.

        Runs the inner jitted apply on a device-resident ``(batch,
        window, channels)`` input with ``block_until_ready`` — no host
        numpy staging, no scaler, no result fetch — so the number is
        dispatch + device compute only.  The gap between this and the
        e2e ``latency_stats()`` percentiles is host/transfer/tunnel
        overhead, which dominates through a remote-tunnel device (e2e
        ~250 ms/hop vs sub-ms device compute in BENCH_r04's serving
        lane) and is what a co-located deployment would shed.

        The result is cached per batch size and folded into
        ``latency_stats()`` as ``device_p50_ms`` / ``host_overhead_p50_ms``.
        Raises ValueError for models without a jitted predict (trees,
        MLlib replicas) — their transform has no single device program
        to time.
        """
        # unwrap + measure via the shared helpers (device_predict_fn /
        # measure_device_latency) so the fleet engine's calibration
        # reports the same decomposition this classifier does
        result = measure_device_latency(
            self.model,
            window=self.window,
            channels=self.channels,
            batch=batch,
            iters=iters,
        )
        self._device_ms[batch] = result
        return result

    def latency_stats(self) -> dict:
        """Per-PREDICT end-to-end wall-clock distribution (ms) over the
        TRAILING window of the last 4096 dispatches (the full session
        since ``reset()`` until that rotates — a deployed 20 Hz session
        runs for days, so the stats stay current and the memory
        constant; ``count`` is therefore capped at the window length,
        not a lifetime dispatch total).

        One sample per dispatched batch: a live hop-by-hop stream gets
        one sample per hop, while a burst/replay push contributes one
        sample per batched predict (events carry the amortized
        per-window share in ``latency_ms``).

        Contract: ``steady_p50_ms`` is ``None`` when there is no
        post-compilation evidence (a cold session that dispatched only
        once) — consumers must treat it as optional, never as 0.  All
        ``*_ms`` keys are e2e (host staging + transfer + device +
        fetch); after a ``device_latency_ms()`` calibration the dict
        also carries ``device_p50_ms`` (device dispatch+compute only)
        and ``host_overhead_p50_ms`` (steady e2e minus device — the
        transfer/tunnel share a co-located deployment would shed).
        """
        if not self._latencies:
            return {"count": 0}
        lat = list(self._latencies)
        # steady = samples after compilation; only the classifier's very
        # first session pays it, and with a single (cold) sample there is
        # no steady evidence at all — report None, not the compile time.
        # (Once the trailing window has rotated past the cold sample the
        # first entry is steady too, but dropping one steady sample is
        # harmless and the distinction is untrackable after rotation.)
        steady = lat[1:] if self._session_starts_cold else lat
        stats = {
            "count": len(lat),
            "p50_ms": round(_percentile(lat, 50), 3),
            "p95_ms": round(_percentile(lat, 95), 3),
            "max_ms": round(max(lat), 3),
            "steady_p50_ms": (
                round(_percentile(steady, 50), 3) if steady else None
            ),
        }
        dev = self._device_ms.get(1) or next(
            iter(self._device_ms.values()), None
        )
        if dev is not None:
            stats["device_p50_ms"] = dev["p50_ms"]
            stats["device_batch"] = dev["batch"]
            e2e_ref = stats["steady_p50_ms"]
            # the overhead subtraction is only meaningful against a
            # batch-1 calibration (hops dispatch single windows) — a
            # batch-k device time against per-hop e2e would understate
            # or zero-clamp the published overhead
            if e2e_ref is not None and dev["batch"] == 1:
                stats["host_overhead_p50_ms"] = round(
                    max(0.0, e2e_ref - dev["p50_ms"]), 3
                )
        return stats

    @property
    def drift_report(self):
        """The attached monitor's latest DriftReport (None without a
        monitor or before the first push)."""
        return self._asm.drift_report

    def label_name(self, label: int) -> str:
        if self.class_names and 0 <= label < len(self.class_names):
            return self.class_names[label]
        return str(label)


def classify_session(
    model,
    samples: np.ndarray,
    *,
    window: int = 200,
    hop: int = 20,
    timing: bool = False,
) -> "SessionResult":
    """Offline sliding-window classification of a full recording.

    Builds the strided ``(k, window, C)`` view (zero-copy) and scores it
    in one batched ``transform`` — the throughput path; equals the
    streaming path's raw labels exactly.

    With ``timing=True`` the result carries the same device-vs-host
    latency decomposition the streaming path reports: ``e2e_ms`` (host
    staging + transfer + device + fetch for the one batched dispatch),
    ``device_p50_ms`` (the compiled predict on a device-resident batch
    of the same shape, ``block_until_ready``, no fetch) and
    ``host_overhead_ms`` — the tunnel/transfer share a serving consumer
    attributes p99 spikes to.  ``device_p50_ms`` is None for models
    without a jitted predict (trees, MLlib replicas).
    """
    samples = np.ascontiguousarray(np.asarray(samples, np.float32))
    if samples.ndim != 2:
        raise ValueError(f"expected (n, channels) samples, got {samples.shape}")
    n = len(samples)
    if n < window:
        raise ValueError(f"recording shorter ({n}) than one window ({window})")
    k = (n - window) // hop + 1
    stride0 = samples.strides[0]
    windows = np.lib.stride_tricks.as_strided(
        samples,
        shape=(k, window, samples.shape[1]),
        strides=(hop * stride0, stride0, samples.strides[1]),
        writeable=False,
    )
    if timing:
        # warm the (k, window, C) program OUTSIDE the timed region —
        # otherwise e2e_ms includes trace+compile and host_overhead_ms
        # reports compilation as tunnel/host overhead, misdirecting the
        # exact attribution this mode exists for (the streaming path
        # warms before timing for the same reason)
        model.transform(windows)
    t0 = time.perf_counter()
    preds = model.transform(windows)
    e2e_ms = (time.perf_counter() - t0) * 1e3
    ends = window + hop * np.arange(k)
    timing_stats = None
    if timing:
        try:
            dev = measure_device_latency(
                model, window=window, channels=samples.shape[1], batch=k
            )
        except ValueError:
            dev = None  # no device program behind this model
        timing_stats = {
            "n_windows": k,
            "e2e_ms": round(e2e_ms, 3),
            "per_window_ms": round(e2e_ms / k, 4),
            "device_p50_ms": None if dev is None else dev["p50_ms"],
            "host_overhead_ms": (
                None
                if dev is None
                else round(max(0.0, e2e_ms - dev["p50_ms"]), 3)
            ),
        }
    return SessionResult(
        t_index=ends,
        labels=np.asarray(preds.prediction, np.int32),
        probability=np.asarray(preds.probability),
        timing=timing_stats,
    )


@dataclasses.dataclass(frozen=True)
class SessionResult:
    """classify_session output: one row per emitted window."""

    t_index: np.ndarray  # (k,) window-end sample indices
    labels: np.ndarray  # (k,)
    probability: np.ndarray  # (k, C)
    timing: dict | None = None  # device-vs-host decomposition of the
    #   one batched dispatch (classify_session(timing=True) only)

    def __len__(self) -> int:
        return len(self.labels)

    def segments(self) -> list[tuple[int, int, int]]:
        """Run-length merge: [(start_t, end_t, label)] over the session,
        the activity timeline a monitoring UI renders (the paper's
        stated use case is elderly-activity monitoring)."""
        if not len(self.labels):
            return []
        out = []
        start = 0
        for i in range(1, len(self.labels)):
            if self.labels[i] != self.labels[start]:
                out.append(
                    (
                        int(self.t_index[start]),
                        int(self.t_index[i - 1]),
                        int(self.labels[start]),
                    )
                )
                start = i
        out.append(
            (
                int(self.t_index[start]),
                int(self.t_index[-1]),
                int(self.labels[start]),
            )
        )
        return out
