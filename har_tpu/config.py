"""Typed configuration for the framework.

The reference hardcodes every hyperparameter as a literal inside the script
(reference Main/main.py:20,80,115,202-207,297,478) and takes only the Spark
master URL from the CLI. Here the whole run is described by dataclasses that
the `har` CLI fills from flags.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Mapping, Sequence

# Default location of the WISDM transformed CSV.  The reference ships the data
# inside its own tree; we read it from the read-only reference mount when
# present and fall back to a synthetic generator (har_tpu.data.synthetic) so
# the framework is self-contained.
REFERENCE_WISDM_CSV = (
    "/root/reference/Main/wisdm_main_ver_0.0/data/wisdm_data.csv"
)


def default_wisdm_path() -> str | None:
    path = os.environ.get("HAR_TPU_WISDM_CSV", REFERENCE_WISDM_CSV)
    return path if os.path.exists(path) else None


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Dataset + split configuration (reference Main/main.py:16-26,80)."""

    dataset: str = "wisdm"  # wisdm | wisdm_raw | ucihar | synthetic
    path: str | None = None
    # Columns dropped by the reference: USER + the 30 histogram-bin columns.
    drop_binned: bool = True
    train_fraction: float = 0.7
    seed: int = 2018
    # How train/test membership is drawn.  "spark" replays the reference's
    # randomSplit bit-for-bit (XORShiftRandom + vector-struct sort; see
    # har_tpu.data.spark_split) — 3,793/1,625 rows for seed 2018, row-exact
    # vs result.txt:105-131.  "bernoulli" is the plain NumPy draw.  "auto"
    # picks spark for the tabular WISDM dataset, bernoulli elsewhere.
    split_method: str = "auto"  # auto | spark | bernoulli
    # Row count for synthetic fallbacks (None → dataset-matching defaults:
    # 5418 tabular rows / 4000 raw windows / 2000 UCI rows); tests shrink
    # it to keep CPU runs fast.
    synthetic_rows: int | None = None

    def resolved_path(self) -> str | None:
        if self.path is not None:
            return self.path
        if self.dataset == "wisdm":
            return default_wisdm_path()
        return None


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Model selection + hyperparameters.

    Defaults mirror the reference estimators:
      - LR:   maxIter=20, regParam=0.3, elasticNetParam=0   (main.py:115)
      - DT:   maxDepth=3                                    (main.py:297)
      - RF:   numTrees=100, maxDepth=4, maxBins=32          (main.py:478)
    """

    name: str = "logistic_regression"
    params: Mapping[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    batch_size: int = 512
    epochs: int = 50
    learning_rate: float = 1e-3
    weight_decay: float = 0.0
    dtype: str = "bfloat16"  # compute dtype for neural models (MXU-friendly)
    seed: int = 0
    checkpoint_dir: str | None = None
    log_every: int = 100


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout for SPMD execution.

    Axis names follow the scaling-book convention: `dp` shards the batch,
    `tp` shards model (feature/hidden) dimensions.  The classical workloads
    use pure DP; neural configs may use both.  Default is single-device;
    pass dp=-1 (or `har train --dp -1`) to spread over all devices.
    """

    dp: int = 1  # -1 → all available devices
    tp: int = 1

    def shape(self, n_devices: int) -> tuple[int, int]:
        if self.dp == 0 or self.dp < -1:
            raise ValueError(
                f"dp={self.dp} is invalid: use a positive device count or "
                "-1 for all available devices"
            )
        if self.tp < 1:
            raise ValueError(f"tp={self.tp} must be >= 1")
        dp = self.dp if self.dp > 0 else max(1, n_devices // self.tp)
        return dp, self.tp


@dataclasses.dataclass(frozen=True)
class TuningConfig:
    """Cross-validation / grid-search (reference Main/main.py:202-212)."""

    num_folds: int = 5
    # Metric used to pick the best grid point.  The reference silently uses
    # the *MAE* RegressionEvaluator for model selection (SURVEY §2 N quirk);
    # we default to accuracy and expose `mae` to replicate the quirk.
    selection_metric: str = "accuracy"
    grid: Mapping[str, Sequence[Any]] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class RunConfig:
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    tuning: TuningConfig | None = None
    output_dir: str = "main_result"
