"""THE partition-rule sharding layer: regex rule tables → spec trees.

One declarative table per model family maps parameter-tree paths to
`PartitionSpec`s; `match_partition_rules` resolves a table against any
concrete param tree (first match wins, scalars always replicate, a
terminal catch-all is required) and `make_shard_and_gather_fns` turns
the resolved spec tree into per-leaf placement/fetch closures.  This is
the ROADMAP's "match_partition_rules refactor": the hand-built spec
trees that used to live in `tensor_parallel` / `zero1` /
`expert_parallel` / `pipeline_parallel` collapse into table lookups
here, and the serving side (`har_tpu.serve.dispatch
.ModelParallelScorer`) places checkpoints through the SAME tables — one
sharding vocabulary for train and serve (the DrJAX framing: placement
is data, not code).

Tables are module-level LITERALS on purpose: harlint's HL007 audit
reads them statically (every leaf of a family's reference tree must be
claimed by exactly one live rule; the catch-all must be terminal), so a
deleted kernel rule or a catch-all hoisted above the kernel rules fails
`har lint` before it can silently serve a replicated model.

Rule semantics:
  - a rule is ``(regex, PartitionSpec)``; the regex is `re.search`-ed
    against the '/'-joined tree path of each leaf (dict keys, attr
    names, or sequence indices — so int8's flat leaf LIST addresses as
    "0", "1", …).
  - first match wins; later rules never see a claimed leaf.
  - scalar leaves (ndim 0, or single-element) replicate regardless of
    the table — there is nothing to shard.
  - a leaf no rule matches is a ``ValueError``: every table must end
    with a catch-all ``(".*", P())``.

Axis convention: tables shard over the mesh's ``tp`` axis (the model
axis of a 2D ``(dp, tp)`` serving mesh — `mesh.create_mesh`); the batch
rides ``dp`` via `sharding.batch_sharding` exactly as before.
"""

from __future__ import annotations

import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from har_tpu.parallel.mesh import TP_AXIS


def tree_path_str(path) -> str:
    """'/'-joined printable form of a tree_flatten_with_path key path
    (dict key, attribute, or sequence index — int8 leaf lists address
    by position)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:  # raw tuple-path entries (tests, hand-built paths)
            parts.append(str(k))
    return "/".join(parts)


def match_rule(rules, name: str):
    """First-match-wins lookup of ONE '/'-joined path in a rule table.

    The scalar-blind primitive under `match_partition_rules`, exposed
    for call sites that place named arguments rather than param leaves
    (shard_map prefix trees built before any params exist — the moe and
    pipeline wrappers)."""
    for pattern, spec in rules:
        if re.search(pattern, name) is not None:
            return spec
    raise ValueError(
        f"no partition rule matched {name!r} — every rule table must "
        "end with a terminal catch-all ('.*', P())"
    )


def match_partition_rules(rules, params):
    """Resolve a rule table against a param tree → PartitionSpec tree.

    ``rules`` is a sequence of ``(regex, PartitionSpec)``; the first
    rule whose regex `re.search`-matches a leaf's '/'-joined path wins.
    Scalar leaves replicate unconditionally.  Raises ``ValueError`` for
    a leaf no rule matches — a table without a terminal catch-all is a
    bug, not a default."""
    def assign(path, leaf):
        if np.ndim(leaf) == 0 or np.size(leaf) == 1:
            return P()  # scalars: nothing to shard, whatever the table says
        return match_rule(rules, tree_path_str(path))

    return jax.tree_util.tree_map_with_path(assign, params)


def make_shard_fns(mesh: Mesh, partition_specs):
    """Per-leaf placement tree for a resolved spec tree: each fn puts a
    host (or replicated-device) leaf onto the mesh in its table layout
    — ONE placement, reused for the life of the program.  Apply with
    ``jax.tree.map(lambda f, x: f(x), fns, tree)``.  This is the half a
    scorer needs at construction; the gather half lives only in
    `make_shard_and_gather_fns` so the launch path never closes over a
    host sync."""
    def shard_fn(spec):
        sharding = NamedSharding(mesh, spec)
        return lambda x: jax.device_put(x, sharding)

    return jax.tree.map(
        shard_fn, partition_specs, is_leaf=lambda s: isinstance(s, P)
    )


def make_shard_and_gather_fns(mesh: Mesh, partition_specs):
    """``(shard_fns, gather_fns)`` trees for a resolved spec tree:
    ``shard_fns`` as in `make_shard_fns`, ``gather_fns`` fetching the
    placed leaves back to host fully assembled (the checkpoint-export
    path — an explicit, rare host sync by design)."""
    def gather_fn(spec):
        del spec  # a device_get assembles any layout
        return lambda x: jax.device_get(x)

    return (
        make_shard_fns(mesh, partition_specs),
        jax.tree.map(
            gather_fn, partition_specs, is_leaf=lambda s: isinstance(s, P)
        ),
    )


def respec_axis(spec, old: str, new: str):
    """A table spec with one mesh-axis name substituted — for wrappers
    that accept a caller-chosen axis name over a default-axis table
    (`make_moe_fn(axis=...)`, `make_pipeline_fn(axis=...)`)."""
    if old == new:
        return spec
    return P(*[new if entry == old else entry for entry in tuple(spec)])


def spec_shard_count(mesh: Mesh, spec) -> int:
    """How many ways a single leaf splits under ``spec`` on ``mesh`` —
    host-side mesh arithmetic for params-bytes accounting."""
    n = 1
    for entry in tuple(spec):
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            if ax is not None:
                n *= int(mesh.shape[ax])
    return n


# --------------------------------------------------------------------
# family tables
#
# LITERAL tables (no comprehensions, no helpers) — harlint HL007's
# table audit parses these with `ast` and replays the first-match-wins
# resolution against REFERENCE_TREES below.  Edit a table and the audit
# re-judges it; delete a kernel rule or hoist the catch-all and
# `har lint` fails.

# Stacks of Flax `nn.Dense` layers (the MLP family, and any model whose
# 2-D kernels are auto-named Dense_0, Dense_1, …): Megatron
# alternation by LAYER PARITY — even layers column-parallel (output dim
# sharded, bias follows), odd layers row-parallel (input dim sharded —
# the previous layer left the activations sharded on hidden).  The
# regexes key on the LAST digit of the layer index, so Dense_10 pairs
# with Dense_0's parity exactly as the natural-order walk in
# `dense_alternating_specs` always produced.
DENSE_MLP_RULES = (
    (r"Dense_\d*[02468]/kernel$", P(None, TP_AXIS)),
    (r"Dense_\d*[13579]/kernel$", P(TP_AXIS, None)),
    (r"Dense_\d*[02468]/bias$", P(TP_AXIS)),
    (r".*", P()),
)

# Transformer1D encoder (har_tpu.models.transformer, unscanned layout —
# the checkpoint form a served model carries): attention qkv
# column-parallel (heads split over tp), proj row-parallel closing the
# pair with one all-reduce; the FFN Dense_0/Dense_1 pair likewise.
# Embedding, norms, and the small head stay replicated (the catch-all).
TRANSFORMER_RULES = (
    (r"qkv/kernel$", P(None, TP_AXIS)),
    (r"qkv/bias$", P(TP_AXIS)),
    (r"proj/kernel$", P(TP_AXIS, None)),
    (r"Dense_0/kernel$", P(None, TP_AXIS)),
    (r"Dense_0/bias$", P(TP_AXIS)),
    (r"Dense_1/kernel$", P(TP_AXIS, None)),
    (r".*", P()),
)

# int8-quantized serving leaves (har_tpu.quantize._Int8Inner.params): a
# flat LIST of program-input leaves — int8 kernels interleaved with the
# f32 remainder, addressed by position — in the same natural traversal
# order the float tree flattens to.  int8 leaves are ordinary program
# inputs and shard like any other ≥2-dim leaf: alternate
# column-/row-parallel by kernel ordinal.  The canonical quantized demo
# pair flattens alphabetically to ``[b1, w1, w2]`` — position 0 is the
# bias (replicated via the catch-all), 1 the int8 up-projection
# (column-parallel), 2 the int8 down-projection (row-parallel).
INT8_RULES = (
    (r"^1$", P(None, TP_AXIS)),
    (r"^2$", P(TP_AXIS, None)),
    (r".*", P()),
)

# ZeRO-1 optimizer state (zero1.make_zero1_fit): every array leaf of
# the flattened-vector optimizer state shards its leading axis over the
# mesh's data axes; scalar leaves (Adam's step count) replicate through
# the matcher's scalar guard.  Built per-mesh because the data axes are
# the mesh's own (``(dp,)``, or ``(dp_dcn, dp)`` on multi-slice pods).
def zero1_rules(axes):
    return ((r".*", P(axes)),)


# Switch-routed MoE (expert_parallel.init_moe_params): the replicated
# router vs the expert stacks' leading E axis, one expert per device on
# the linear ``ep`` mesh.  Resolved by NAME (`match_rule`) into the
# moe shard_map's in_specs prefix tree.
MOE_RULES = (
    (r"^router$", P()),
    (r"^experts(/|$)", P("ep")),
    (r".*", P()),
)

# GPipe pipeline (pipeline_parallel.make_pipeline_fn): stage-stacked
# params split their leading S axis over the linear ``pp`` mesh;
# the microbatched activations (and the collected output) replicate.
PIPELINE_RULES = (
    (r"^stacked_params$", P("pp")),
    (r".*", P()),
)

RULE_TABLES = {
    "dense_mlp": DENSE_MLP_RULES,
    "transformer": TRANSFORMER_RULES,
    "int8": INT8_RULES,
    "moe": MOE_RULES,
}

# Reference trees the HL007 audit resolves each table against: one
# ``(path, ndim, placement)`` row per leaf of the family's canonical
# param tree, ``placement`` declaring the INTENT — "shard" leaves must
# be claimed by a live non-terminal rule carrying a real axis,
# "rep" leaves must resolve replicated.  A deleted kernel rule turns a
# "shard" row into a catch-all hit (unmatched-leaf finding); a
# catch-all hoisted first starves every later rule (dead-rule finding).
REFERENCE_TREES = {
    "dense_mlp": (
        ("Dense_0/kernel", 2, "shard"),
        ("Dense_0/bias", 1, "shard"),
        ("Dense_1/kernel", 2, "shard"),
        ("Dense_1/bias", 1, "rep"),
        ("Dense_10/kernel", 2, "shard"),
        ("Dense_10/bias", 1, "shard"),
    ),
    "transformer": (
        ("EncoderBlock_0/qkv/kernel", 2, "shard"),
        ("EncoderBlock_0/qkv/bias", 1, "shard"),
        ("EncoderBlock_0/proj/kernel", 2, "shard"),
        ("EncoderBlock_0/proj/bias", 1, "rep"),
        ("EncoderBlock_0/Dense_0/kernel", 2, "shard"),
        ("EncoderBlock_0/Dense_0/bias", 1, "shard"),
        ("EncoderBlock_0/Dense_1/kernel", 2, "shard"),
        ("EncoderBlock_0/Dense_1/bias", 1, "rep"),
        ("EncoderBlock_0/LayerNorm_0/scale", 1, "rep"),
        ("EncoderBlock_0/LayerNorm_0/bias", 1, "rep"),
        ("LayerNorm_0/scale", 1, "rep"),
        ("embed/kernel", 2, "rep"),
        ("embed/bias", 1, "rep"),
        ("head/kernel", 2, "rep"),
        ("head/bias", 1, "rep"),
    ),
    "int8": (
        ("0", 1, "rep"),
        ("1", 2, "shard"),
        ("2", 2, "shard"),
    ),
    "moe": (
        ("router", 2, "rep"),
        ("experts/w1", 3, "shard"),
        ("experts/b1", 2, "shard"),
        ("experts/w2", 3, "shard"),
        ("experts/b2", 2, "shard"),
    ),
}


def alternating_rules(
    params, tp_axis: str = TP_AXIS, *, kernels_only: bool = False
):
    """GENERATED table: Megatron alternation over any param tree.

    Walks the tree in the same natural order `dense_alternating_specs`
    always used ((prefix, numeric-suffix) component sort, so Dense_10
    follows Dense_9) and emits one exact-path rule per 2-D kernel-like
    leaf — even ordinals column-parallel, odd row-parallel, a bias (or
    1-D follower) after a column-parallel kernel sharded with it — plus
    the terminal catch-all.  This is how arbitrary trees (JitDemoModel's
    ``w1/b1/w2``, int8 leaf lists, CNN heads) get a family table without
    hand-writing one; for pure Dense stacks it resolves identically to
    ``DENSE_MLP_RULES``.

    ``kernels_only=True`` restricts the alternation to leaves NAMED
    ``kernel`` (the historical `dense_alternating_specs` contract:
    LSTM cell matrices and other 2-D non-kernel leaves replicate)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def natural_key(path):
        def component(k):
            k = getattr(k, "key", getattr(k, "idx", k))
            head, _, tail = str(k).rpartition("_")
            return (head, int(tail)) if tail.isdigit() else (str(k), -1)

        return tuple(component(k) for k in path)

    ordered = sorted(flat, key=lambda pl: natural_key(pl[0]))
    rules = []
    kernel_index = 0
    column_prefixes = set()
    prev_was_column = False
    for path, leaf in ordered:
        name = tree_path_str(path)
        tail = str(
            getattr(path[-1], "key", getattr(path[-1], "idx", path[-1]))
        )
        is_kernel = (
            tail == "kernel" if kernels_only else tail != "bias"
        )
        if np.ndim(leaf) == 2 and is_kernel:
            column = kernel_index % 2 == 0
            if column:
                column_prefixes.add(name.rpartition("/")[0])
            kernel_index += 1
            rules.append((
                rf"^{re.escape(name)}$",
                P(None, tp_axis) if column else P(tp_axis, None),
            ))
            prev_was_column = column
        elif np.ndim(leaf) == 1 and tail.isdigit() and prev_was_column:
            # positional (list) form: the 1-D follower of a
            # column-parallel kernel is its bias — shard with it
            rules.append((rf"^{re.escape(name)}$", P(tp_axis)))
            prev_was_column = False
        else:
            prev_was_column = False
    for path, leaf in ordered:
        name = tree_path_str(path)
        tail = str(
            getattr(path[-1], "key", getattr(path[-1], "idx", path[-1]))
        )
        if tail == "bias" and name.rpartition("/")[0] in column_prefixes:
            rules.append((rf"^{re.escape(name)}$", P(tp_axis)))
    rules.append((r".*", P()))
    return tuple(rules)


def rules_for_params(params, tp_axis: str = TP_AXIS):
    """Family auto-detection: the table a param tree serves under.

    Paths carrying the transformer vocabulary (``qkv/kernel``) get
    ``TRANSFORMER_RULES``; trees whose every ≥2-dim leaf is an
    auto-named ``Dense_k/kernel`` get ``DENSE_MLP_RULES``; everything
    else (demo models, int8 leaf lists, conv stacks) gets a generated
    `alternating_rules` table over its own exact paths."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    names = [tree_path_str(p) for p, _ in flat]
    if any(n.endswith("qkv/kernel") for n in names):
        return TRANSFORMER_RULES
    multi = [
        n for (p, leaf), n in zip(flat, names) if np.ndim(leaf) >= 2
    ]
    if multi and all(
        re.search(r"Dense_\d+/kernel$", n) for n in multi
    ):
        return DENSE_MLP_RULES
    return alternating_rules(params, tp_axis)


def shard_divisibility_check(params, specs, mesh: Mesh) -> None:
    """Refuse layouts whose sharded dims do not divide their mesh-axis
    extent — a silently padded placement would change the served
    math."""
    def check(path, x, s):
        for dim, entry in zip(np.shape(x), tuple(s)):
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                if ax is None:
                    continue
                # host-side mesh-shape arithmetic at scorer
                # construction — no device value is touched
                # harlint: host-ok
                n = int(mesh.shape[ax])
                if dim % n:
                    raise ValueError(
                        f"param {tree_path_str(path)!r} dim {dim} not "
                        f"divisible by mesh axis {ax!r}={n} (spec {s})"
                    )

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    spec_flat = jax.tree.leaves(
        specs, is_leaf=lambda t: isinstance(t, P)
    )
    for (path, leaf), s in zip(flat, spec_flat):
        check(path, leaf, s)
