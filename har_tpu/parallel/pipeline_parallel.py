"""Pipeline parallelism: GPipe-style microbatching over a ``pp`` mesh axis.

The fourth parallelism axis (with dp/tp/sp): homogeneous stages are laid
out one per device, activations rotate around the ring with
`jax.lax.ppermute`, and microbatches stream through so every stage is busy
once the pipeline fills (the classic GPipe schedule: M + S − 1 ticks for
M microbatches over S stages, bubble fraction (S−1)/(M+S−1)).

Design constraints, chosen for XLA:
  - **Homogeneous stages.** Every stage applies the same `stage_fn` with
    its own parameter slice (stacked on a leading S axis, sharded over
    ``pp``).  Input/output projections that differ per position run
    replicated outside the pipelined block — this keeps the rotating
    activation a fixed shape, which is what makes the whole schedule one
    `lax.scan` with static shapes.
  - **In-graph schedule.** The tick loop is a `lax.scan`, the stage-0
    feed and last-stage collect are `where`-masked — no host round trips
    per tick, and the program differentiates (ppermute and scan both have
    transpose rules), so the same function serves forward and training.

The reference has nothing to pipeline (its models are single-stage;
SURVEY §2c.3) — this exists for the neural families and for parity with
the multi-axis sharding contract (`__graft_entry__.dryrun_multichip`).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

PP_AXIS = "pp"


def pipeline_mesh(pp: int = -1, devices: list | None = None) -> Mesh:
    """1-D ``pp`` mesh (stage i on device i)."""
    from har_tpu.parallel.mesh import linear_mesh

    return linear_mesh(pp, PP_AXIS, devices)


def make_pipeline_fn(
    stage_fn: Callable, mesh: Mesh, axis: str = PP_AXIS
) -> Callable:
    """Build ``f(stacked_params, x) -> y`` running S pipelined stages.

    ``stage_fn(params, a) -> a`` must preserve the activation shape
    (homogeneous stages).  ``stacked_params`` leaves carry a leading S
    axis (stage i's slice lives on device i); ``x`` is (M, mb, d)
    microbatches, replicated in and out (the activation shapes here are
    small; shard the batch dim with an outer dp axis when they aren't).
    """
    s = mesh.shape[axis]
    perm = [(j, (j + 1) % s) for j in range(s)]

    def pipelined(stacked_params, x):
        m = x.shape[0]
        idx = jax.lax.axis_index(axis)
        # in_specs=P(axis) split the stacked S axis across devices: each
        # local slice must hold exactly ONE stage — a stage count that is
        # a larger multiple of the mesh size would silently drop stages
        for leaf in jax.tree.leaves(stacked_params):
            if leaf.shape[0] != 1:
                raise ValueError(
                    f"stage count {leaf.shape[0] * s} != pp mesh size {s}"
                    " — stack exactly one stage per pipeline device"
                )
        params = jax.tree.map(lambda p: p[0], stacked_params)

        def tick(carry, t):
            state, outbuf = carry
            # stage 0 feeds microbatch t while t < M, zeros during drain
            x_t = jax.lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            feed = jnp.where(t < m, 1.0, 0.0) * x_t
            inp = jnp.where(idx == 0, feed, state)
            out = stage_fn(params, inp)
            nxt = jax.lax.ppermute(out, axis, perm)
            # last stage collects out for microbatch t-(S-1)
            pos = jnp.clip(t - (s - 1), 0, m - 1)
            cur = jax.lax.dynamic_index_in_dim(
                outbuf, pos, 0, keepdims=False
            )
            write = (idx == s - 1) & (t >= s - 1)
            outbuf = jax.lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(write, out, cur), pos, 0
            )
            return (nxt, outbuf), None

        state0 = jnp.zeros_like(x[0])
        outbuf0 = jnp.zeros_like(x)
        (_, outbuf), _ = jax.lax.scan(
            tick, (state0, outbuf0), jnp.arange(m + s - 1)
        )
        # result lives on the last stage; mask + psum broadcasts it
        return jax.lax.psum(
            jnp.where(idx == s - 1, 1.0, 0.0) * outbuf, axis
        )

    # stage stacks split their leading S axis, activations replicate —
    # the PIPELINE_RULES table's layout, looked up by argument name
    from har_tpu.parallel.rules import (
        PIPELINE_RULES,
        match_rule,
        respec_axis,
    )

    return jax.shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(
            respec_axis(
                match_rule(PIPELINE_RULES, "stacked_params"),
                PP_AXIS, axis,
            ),
            match_rule(PIPELINE_RULES, "x"),
        ),
        out_specs=match_rule(PIPELINE_RULES, "y"),
        check_vma=False,
    )


def stack_stage_params(param_list):
    """[stage0_params, stage1_params, ...] → stacked (S, ...) pytree."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)
