"""Device-mesh construction for SPMD execution.

This replaces the reference's entire driver/executor topology (Spark master
URL at reference Main/main.py:8, Netty RPC + treeAggregate under MLlib, see
SURVEY §2b/§5.8): instead of a cluster manager scheduling tasks onto
executors, every device in a `jax.sharding.Mesh` runs the same compiled XLA
program, and cross-device reductions are in-graph collectives (`psum` over
the `dp` axis is the moral equivalent of Spark's treeAggregate).

Axis convention (scaling-book style):
  - ``dp``: data parallelism — shards the batch/row dimension.
  - ``tp``: tensor parallelism — shards feature/hidden dimensions.

Multi-host: callers run `jax.distributed.initialize()` before building a
mesh; `jax.devices()` then spans all hosts and XLA routes collectives over
ICI within a slice and DCN across slices automatically.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

DP_AXIS = "dp"
TP_AXIS = "tp"


def create_mesh(
    dp: int = -1,
    tp: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a 2-D (dp, tp) mesh.

    ``dp=-1`` means "all remaining devices after tp".  tp devices are placed
    on the fastest-varying axis so tensor-parallel collectives ride the
    nearest ICI links.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if tp < 1 or n % tp:
        raise ValueError(f"tp={tp} must divide device count {n}")
    if dp == -1:
        dp = n // tp
    if dp * tp != n:
        raise ValueError(f"dp*tp={dp * tp} != device count {n}")
    grid = np.asarray(devices).reshape(dp, tp)
    return Mesh(grid, (DP_AXIS, TP_AXIS))


DP_DCN_AXIS = "dp_dcn"


def create_multihost_mesh(
    num_slices: int,
    tp: int = 1,
    devices: list | None = None,
) -> Mesh:
    """3-D (dp_dcn, dp, tp) mesh for multi-slice pods.

    The slice axis (`dp_dcn`) is outermost and slowest-varying, so data
    parallelism across slices reduces over DCN exactly once per step
    while tensor-parallel collectives stay on the innermost (fastest)
    ICI axis — the standard hybrid layout.  Devices must be ordered
    slice-major (which `jax.devices()` is on multi-slice TPU after
    `initialize_distributed()`).  Gradient reduction over both dp axes:
    ``psum(psum(g, 'dp'), 'dp_dcn')`` or `psum` over the tuple.

    Single-host testing: any device list divisible by num_slices×tp
    works — the CPU test mesh treats virtual device groups as slices.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if num_slices < 1 or n % num_slices:
        raise ValueError(
            f"num_slices={num_slices} must divide device count {n}"
        )
    per_slice = n // num_slices
    if tp < 1 or per_slice % tp:
        raise ValueError(
            f"tp={tp} must divide per-slice device count {per_slice}"
        )
    grid = np.asarray(devices).reshape(num_slices, per_slice // tp, tp)
    return Mesh(grid, (DP_DCN_AXIS, DP_AXIS, TP_AXIS))


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Data-parallel axes present in a mesh, outermost (DCN) first.

    The single source of truth for "which axes shard the batch" — the
    trainers, batch-sharding helpers, and GSPMD constraints all consult
    this so hybrid multi-slice meshes behave identically everywhere.
    """
    return tuple(a for a in (DP_DCN_AXIS, DP_AXIS) if a in mesh.shape)


def data_shard_count(mesh: Mesh) -> int:
    """How many ways the batch dimension splits on this mesh."""
    # host-side mesh-shape arithmetic (a dict of ints), evaluated once
    # at scorer construction — no device value is touched
    # harlint: host-ok
    return int(
        np.prod([mesh.shape[a] for a in data_axes(mesh)], dtype=np.int64)
    ) if data_axes(mesh) else 1


def model_shard_count(mesh: Mesh) -> int:
    """How many ways the model (hidden) dimension splits on this mesh —
    the ``tp`` extent of a 2D ``(dp, tp)`` serving mesh, 1 when the
    mesh has no model axis."""
    # host-side mesh-shape arithmetic, like data_shard_count
    # harlint: host-ok
    return int(mesh.shape.get(TP_AXIS, 1))


def linear_data_shard_index(mesh: Mesh):
    """Traced linear shard id across every data axis (inside shard_map).

    Keeps per-shard rng folds unique on hybrid meshes: slice-major,
    matching the device order `create_multihost_mesh` lays out.
    """
    idx = jax.lax.axis_index(DP_AXIS)
    if DP_DCN_AXIS in mesh.shape:
        idx = jax.lax.axis_index(DP_DCN_AXIS) * mesh.shape[DP_AXIS] + idx
    return idx


def linear_mesh(n: int, axis: str, devices: list | None = None) -> Mesh:
    """1-D mesh over ``n`` devices with one named axis (pp/ep layouts)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    if n == -1:
        n = len(devices)
    if n < 1 or n > len(devices):
        raise ValueError(f"{axis}={n} needs 1..{len(devices)} devices")
    return Mesh(np.asarray(devices[:n]), (axis,))


def single_device_mesh(device=None) -> Mesh:
    """A 1×1 mesh — lets every code path be mesh-shaped even on one chip."""
    device = device or jax.devices()[0]
    return create_mesh(dp=1, tp=1, devices=[device])


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host SPMD bootstrap (call once per host before building a mesh).

    The Spark equivalent is the cluster master URL + executor registration
    (reference Main/main.py:8, README.md:5-8); here every host runs this
    and the same program, after which `jax.devices()` spans the whole pod
    and XLA routes collectives over ICI within a slice / DCN across
    slices.  Arguments default to the TPU metadata environment (on Cloud
    TPU pods `jax.distributed.initialize()` autodetects everything).
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
