"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support for the neural family.  The sequence dimension is
sharded over the ``sp`` mesh axis; each device holds a Q/K/V block.  K/V
blocks rotate around the ring with `lax.ppermute` while every device
accumulates its Q-block's attention with the numerically-stable streaming
softmax (flash-attention style running max / numerator / denominator), so
the result is *exact* full attention — only ever materializing
(Tq/sp × Tk/sp) score blocks — and the K/V transfers overlap compute
around the ICI ring.

The reference has nothing comparable (its sequence dim is pre-collapsed,
SURVEY §5.7); this is a new capability the TPU design makes first-class.

Layout: (batch, seq, heads, head_dim) — batch can additionally be sharded
over ``dp`` (the two axes compose; see tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _axis_size(axis_name: str) -> int:
    """Mesh-axis size; jax.lax.axis_size only exists on newer jax."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Reference O(T²) attention, (B, T, H, D) layout, no masking."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Exact attention with sequence sharded over ``axis_name``.

    Must be called inside `shard_map` (or `pmap`) with q/k/v holding the
    *local* sequence block, shape (B, T_local, H, D).  Returns the local
    block of the attention output, same shape.
    """
    axis_size = _axis_size(axis_name)
    scale = q.shape[-1] ** -0.5
    b, t_q, h, d = q.shape

    # ring: shard i sends to shard (i+1) — after `axis_size` steps every
    # device has seen every K/V block
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, _):
        k_blk, v_blk, m, num, den = carry
        # scores + streaming-softmax state accumulate in f32 regardless of
        # the input dtype (flash-attention convention): bf16 running
        # max/num/den would compound rounding error every ring step
        s = jnp.einsum(
            "bqhd,bkhd->bhqk",
            q,
            k_blk,
            preferred_element_type=jnp.float32,
        ) * scale  # (B,H,Tq,Tk)
        blk_max = s.max(axis=-1)  # (B,H,Tq)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)  # rescale old accumulators
        p = jnp.exp(s - new_m[..., None])  # (B,H,Tq,Tk)
        num = num * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd",
            p,
            v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        den = den * corr + p.sum(axis=-1)
        k_blk, v_blk = jax.lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (k_blk, v_blk, new_m, num, den), None

    m0 = jnp.full((b, h, t_q), -jnp.inf, jnp.float32)
    num0 = jnp.zeros((b, h, t_q, d), jnp.float32)
    den0 = jnp.zeros((b, h, t_q), jnp.float32)
    (_, _, m, num, den), _ = jax.lax.scan(
        step, (k, v, m0, num0, den0), None, length=axis_size
    )
    out = (num / den[..., None]).astype(q.dtype)  # (B,H,Tq,D)
    return out.transpose(0, 2, 1, 3)  # (B,Tq,H,D)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    block: int = 0,
) -> jax.Array:
    """Ring attention whose per-hop LOCAL block runs the Pallas kernel.

    `ring_attention` materializes a (B, H, Tq_local, Tk_local) score
    tensor per hop — fine at small local blocks, the HBM hog once
    T_local grows.  Here each hop computes its local contribution with
    `flash_attention_with_lse` (O(block) VMEM, scores never leave the
    chip) and hops merge by exact logaddexp reweighting:

        out = Σ_i out_i · exp(lse_i − L),  L = log Σ_i exp(lse_i)

    which is the same online-softmax algebra the kernel runs internally,
    applied once per ring hop.  ``block=0`` picks the largest usable
    block (pick_block).  Must be called inside `shard_map` like
    `ring_attention`; gradients flow via recompute of this forward
    (jax.checkpoint-friendly: everything is jittable collectives).
    """
    from har_tpu.ops.flash_attention import (
        flash_attention_with_lse,
        pick_block,
    )

    axis_size = _axis_size(axis_name)
    b, t_q, h, d = q.shape
    blk = block or pick_block(k.shape[1])
    if not blk:
        raise ValueError(
            f"no usable flash block for local T={k.shape[1]}; pass "
            "block= or use ring_attention"
        )
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, _):
        k_blk, v_blk, out_acc, lse_acc = carry
        out_i, lse_i = flash_attention_with_lse(
            q, k_blk, v_blk, block_q=min(blk, t_q), block_k=blk
        )  # (B,T,H,D), (B,H,T)
        lse_new = jnp.logaddexp(lse_acc, lse_i)
        w_old = jnp.exp(lse_acc - lse_new)  # (B,H,T)
        w_new = jnp.exp(lse_i - lse_new)
        reweigh = lambda w: w.transpose(0, 2, 1)[..., None]  # (B,T,H,1)
        out_acc = (
            out_acc * reweigh(w_old)
            + out_i.astype(jnp.float32) * reweigh(w_new)
        )
        k_blk, v_blk = jax.lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (k_blk, v_blk, out_acc, lse_new), None

    out0 = jnp.zeros((b, t_q, h, d), jnp.float32)
    lse0 = jnp.full((b, h, t_q), -jnp.inf, jnp.float32)
    (_, _, out, _), _ = jax.lax.scan(
        step, (k, v, out0, lse0), None, length=axis_size
    )
    return out.astype(q.dtype)
