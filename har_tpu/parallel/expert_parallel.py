"""Expert parallelism: switch-routed MoE with `all_to_all` dispatch.

The fifth parallelism axis (dp/tp/pp/sp/ep): E feed-forward experts live
one-per-device on an ``ep`` mesh axis, a top-1 (switch) router assigns
each token an expert, and two `jax.lax.all_to_all` collectives carry
tokens to their expert's device and back.  Dispatch/combine are one-hot
einsums (the Mesh-TensorFlow/GShard formulation) so the whole layer is
static-shape MXU work — no gathers, no dynamic shapes, differentiable end
to end (`all_to_all` has a transpose rule, so the same function trains).

Capacity semantics: each expert processes at most ``capacity`` tokens per
shard; beyond it, tokens are *dropped* (their combine weight is zero and
they contribute nothing) — the standard switch-transformer behavior.
``dropless_capacity(n_local)`` returns the capacity at which dropping is
impossible, which the exactness tests use.

Like pipeline parallelism, nothing in the reference needs this (its
models are single-expert by construction — SURVEY §2c.3); it completes
the mesh-axis vocabulary for the neural families and the multi-axis
driver contract.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

EP_AXIS = "ep"


def expert_mesh(ep: int = -1, devices: list | None = None) -> Mesh:
    """1-D ``ep`` mesh (expert i on device i)."""
    from har_tpu.parallel.mesh import linear_mesh

    return linear_mesh(ep, EP_AXIS, devices)


def dropless_capacity(n_local: int) -> int:
    """Capacity at which no token can be dropped (worst case: every local
    token routes to the same expert)."""
    return n_local


def init_moe_params(
    rng: jax.Array, num_experts: int, hidden: int, ff: int
) -> dict:
    """Router (replicated) + stacked expert FFNs (leading E axis)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    scale1 = (2.0 / hidden) ** 0.5
    scale2 = (2.0 / ff) ** 0.5
    return {
        "router": jax.random.normal(k1, (hidden, num_experts)) * 0.02,
        "experts": {
            "w1": jax.random.normal(k2, (num_experts, hidden, ff)) * scale1,
            "b1": jnp.zeros((num_experts, ff)),
            "w2": jax.random.normal(k3, (num_experts, ff, hidden)) * scale2,
            "b2": jnp.zeros((num_experts, hidden)),
        },
    }


def _expert_ffn(p, x):
    return jax.nn.relu(x @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]


def make_moe_fn(
    mesh: Mesh, capacity: int, axis: str = EP_AXIS
) -> Callable:
    """Build ``f(params, x) -> (y, aux)`` for a switch-routed MoE layer.

    ``x`` is (n, h) with n sharded over ``ep`` (tokens are data-sharded;
    experts are model-sharded — the axis serves both roles, as in real
    MoE deployments).  ``params["experts"]`` leaves carry a leading E
    axis, one expert per device.  Returns the mixed output and an aux
    dict with the load-balancing loss (switch-transformer's f·P dot) and
    the per-expert assignment fractions.
    """
    e = mesh.shape[axis]

    def moe(params, x):
        for leaf in jax.tree.leaves(params["experts"]):
            if leaf.shape[0] != 1:
                raise ValueError(
                    f"expert count {leaf.shape[0] * e} != ep mesh size {e}"
                    " — stack exactly one expert per device"
                )
        nl, h = x.shape
        logits = x @ params["router"]  # (nl, E)
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)  # top-1 routing
        gate = jnp.take_along_axis(probs, expert[:, None], 1)[:, 0]

        onehot = jax.nn.one_hot(expert, e, dtype=x.dtype)  # (nl, E)
        # position of each token within its expert's capacity buffer;
        # tokens past capacity drop out here — one_hot maps their
        # out-of-range pos_id to an all-zero row
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
        pos_id = pos.sum(-1).astype(jnp.int32)
        dispatch = (
            onehot[:, :, None]
            * jax.nn.one_hot(pos_id, capacity, dtype=x.dtype)[:, None, :]
        )  # (nl, E, C)
        combine = dispatch * gate[:, None, None]

        # tokens → expert devices: (E, C, h) → exchange → (S, C, h),
        # the capacity slots every shard routed to MY expert
        ein = jnp.einsum("nec,nh->ech", dispatch, x)
        recv = jax.lax.all_to_all(
            ein, axis, split_axis=0, concat_axis=0
        )
        my_expert = jax.tree.map(lambda p: p[0], params["experts"])
        out = _expert_ffn(my_expert, recv.reshape(e * capacity, h))
        # back to the token owners: shard j's row i holds outputs bound
        # for shard i; the second all_to_all completes the round trip
        send = jax.lax.all_to_all(
            out.reshape(e, capacity, h), axis,
            split_axis=0, concat_axis=0,
        )
        y = jnp.einsum("nec,ech->nh", combine, send)

        # switch load-balance loss: E · Σ_e fraction_e · mean-prob_e,
        # both averaged over the GLOBAL batch
        frac = jax.lax.pmean(onehot.mean(0), axis)
        mean_prob = jax.lax.pmean(probs.mean(0), axis)
        aux = {
            "load_balance_loss": e * jnp.sum(frac * mean_prob),
            "expert_fraction": frac,
        }
        return y, aux

    # router replicated, expert stacks split on their leading E axis,
    # tokens split on the batch axis; aux scalars replicated — the
    # layout is the MOE_RULES table's, looked up by argument name
    from har_tpu.parallel.rules import MOE_RULES, match_rule, respec_axis

    param_specs = {
        "router": respec_axis(
            match_rule(MOE_RULES, "router"), EP_AXIS, axis
        ),
        "experts": respec_axis(
            match_rule(MOE_RULES, "experts"), EP_AXIS, axis
        ),
    }
    return jax.shard_map(
        moe,
        mesh=mesh,
        in_specs=(param_specs, P(axis)),
        out_specs=(P(axis), P()),
        check_vma=False,
    )


def moe_dense_reference(params, x):
    """Every-token-through-its-expert, no parallelism — the exactness
    oracle for `make_moe_fn` at dropless capacity."""
    logits = x @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], 1)[:, 0]
    e = params["experts"]["w1"].shape[0]
    outs = jnp.stack(
        [
            _expert_ffn(
                jax.tree.map(lambda p: p[i], params["experts"]), x
            )
            for i in range(e)
        ],
        axis=1,
    )  # (n, E, h)
    sel = jnp.take_along_axis(
        outs, expert[:, None, None].repeat(x.shape[-1], -1), 1
    )[:, 0]
    return gate[:, None] * sel
