"""Data-parallel training steps over a device mesh.

Replaces the reference's only parallelism strategy — Spark row-partitioned
fit/evaluate with ``treeAggregate`` reductions (SURVEY §2c.1; reference
Main/main.py:8 master URL) — with SPMD: the batch is sharded over the
``dp`` mesh axis, each device computes gradients on its shard inside one
compiled program, and `jax.lax.psum` over ``dp`` reduces them across ICI.

Two styles are provided:

- :func:`make_dp_train_step` — explicit `shard_map` with a hand-written
  `psum`; what the scaling-book calls the "you own the collectives" mode.
  Used by the neural trainer where per-step control matters.
- :func:`jit_replicated` — sharding-annotated `jit`; XLA infers the same
  collectives from in/out shardings.  Used for whole-dataset classical fits
  (LR/DT/RF) where the program is one big reduction anyway.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from har_tpu.parallel.mesh import DP_AXIS

Pytree = Any


def make_dp_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    donate: bool = True,
    n_batch: int = 2,
) -> Callable:
    """Build ``step(params, opt_state, *batch, mask) -> (params, opt_state, loss)``.

    ``loss_fn(params, *batch, mask)`` must return the *sum* of per-example
    losses on the local shard plus the local example count, as a pair
    ``(loss_sum, count)`` — the step psums both over ``dp`` so the global
    mean is exact even with padding (mask=0 rows contribute nothing).
    Params and optimizer state are replicated; batch arrays are sharded on
    their leading axis.
    """

    def local_step(params, opt_state, *batch_and_mask):
        *batch, mask = batch_and_mask

        def local_sum(p):
            loss_sum, count = loss_fn(p, *batch, mask)
            return loss_sum, count

        (loss_sum, count), grads = jax.value_and_grad(
            local_sum, has_aux=True
        )(params)
        # The explicit all-reduce over ICI: sum of per-shard loss/grad/count
        # (Spark's treeAggregate, as one in-graph collective).
        loss_sum, count, grads = jax.lax.psum(
            (loss_sum, count, grads), DP_AXIS
        )
        count = jnp.maximum(count, 1.0)
        grads = jax.tree.map(lambda g: g / count, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss_sum / count

    replicated = P()
    batched = P(DP_AXIS)
    step = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(replicated, replicated) + (batched,) * (n_batch + 1),
        out_specs=(replicated, replicated, replicated),
        check_vma=False,
    )
    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def jit_replicated(
    fn: Callable,
    mesh: Mesh,
    batch_argnums: tuple[int, ...] = (0,),
    **jit_kwargs,
) -> Callable:
    """jit ``fn`` with its batch args sharded over dp and outputs replicated.

    XLA inserts the all-reduces implied by the sharding — the declarative
    twin of :func:`make_dp_train_step` for one-shot whole-dataset programs.
    ``fn`` must have a fixed positional signature (jit requires one
    in_sharding per positional argument).
    """
    params = inspect.signature(fn).parameters.values()
    if any(
        p.kind
        in (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        for p in params
    ):
        raise ValueError("jit_replicated requires a fixed-arity function")
    n_args = len(
        [
            p
            for p in params
            if p.kind
            in (
                inspect.Parameter.POSITIONAL_ONLY,
                inspect.Parameter.POSITIONAL_OR_KEYWORD,
            )
        ]
    )

    def in_sharding(i):
        if i in batch_argnums:
            return NamedSharding(mesh, P(DP_AXIS))
        return NamedSharding(mesh, P())

    in_shardings = tuple(in_sharding(i) for i in range(n_args))
    return jax.jit(
        fn,
        in_shardings=in_shardings,
        out_shardings=NamedSharding(mesh, P()),
        **jit_kwargs,
    )
