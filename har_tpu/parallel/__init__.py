"""SPMD parallelism: device meshes, shardings, data-parallel steps.

TPU-native replacement for the reference's Spark cluster machinery
(driver/executor RPC, row partitioning, treeAggregate — SURVEY §2b, §5.8).
"""

from har_tpu.parallel.mesh import (
    DP_AXIS,
    DP_DCN_AXIS,
    TP_AXIS,
    create_mesh,
    create_multihost_mesh,
    single_device_mesh,
)
from har_tpu.parallel.sharding import (
    batch_sharding,
    pad_to_multiple,
    replicated,
    shard_batch,
)
from har_tpu.parallel.data_parallel import jit_replicated, make_dp_train_step
from har_tpu.parallel.rules import (
    DENSE_MLP_RULES,
    INT8_RULES,
    MOE_RULES,
    PIPELINE_RULES,
    RULE_TABLES,
    TRANSFORMER_RULES,
    alternating_rules,
    make_shard_and_gather_fns,
    make_shard_fns,
    match_partition_rules,
    match_rule,
    rules_for_params,
)
from har_tpu.parallel.tensor_parallel import (
    dense_alternating_specs,
    make_gspmd_scan_fit,
    shard_params,
)
from har_tpu.parallel.pipeline_parallel import (
    PP_AXIS,
    make_pipeline_fn,
    pipeline_mesh,
    stack_stage_params,
)
from har_tpu.parallel.expert_parallel import (
    EP_AXIS,
    expert_mesh,
    init_moe_params,
    make_moe_fn,
)

__all__ = [
    "DENSE_MLP_RULES",
    "INT8_RULES",
    "MOE_RULES",
    "PIPELINE_RULES",
    "RULE_TABLES",
    "TRANSFORMER_RULES",
    "alternating_rules",
    "make_shard_and_gather_fns",
    "make_shard_fns",
    "match_partition_rules",
    "match_rule",
    "rules_for_params",
    "DP_DCN_AXIS",
    "create_multihost_mesh",
    "EP_AXIS",
    "expert_mesh",
    "init_moe_params",
    "make_moe_fn",
    "PP_AXIS",
    "make_pipeline_fn",
    "pipeline_mesh",
    "stack_stage_params",
    "dense_alternating_specs",
    "make_gspmd_scan_fit",
    "shard_params",
    "DP_AXIS",
    "TP_AXIS",
    "create_mesh",
    "single_device_mesh",
    "batch_sharding",
    "replicated",
    "pad_to_multiple",
    "shard_batch",
    "jit_replicated",
    "make_dp_train_step",
]
