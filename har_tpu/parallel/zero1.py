"""ZeRO-1 data parallelism: optimizer state sharded over the data axes.

The reference's only parallelism is Spark's data-parallel fit with all
model/optimizer state held by the driver (SURVEY §2c.1; the L-BFGS
history lives driver-side in `Main/main.py:115`'s MLlib call stack).
The TPU trainers here replicate params AND optimizer state on every
shard — fine at HAR sizes, but the optimizer state (Adam: two extra f32
copies of every parameter) is the first thing that stops fitting as
models grow.  ZeRO-1 shards exactly that state while keeping the simple
replicated-params / psum-grads flow:

  per step:  psum full grads (as plain dp) → each shard updates only
  its 1/N contiguous slice of the FLATTENED parameter vector, using its
  1/N of the optimizer state → ``all_gather(tiled)`` reassembles the
  full params for the next forward.

Collectives per step: the same grad psum as plain dp, plus one
params/N all-gather over ICI.  Per-device optimizer memory drops from
2·D to 2·D/N floats.  The update math (Adam + decoupled weight decay +
schedule) is elementwise, so slicing the flattened vector computes the
IDENTICAL result to the replicated trainer — pinned by test against
``Trainer`` on the same schedule.

ZeRO-1 is about where optimizer state LIVES, not a separate trainer:
``train.Trainer(..., zero1=True)`` swaps its scanned fit for this one
and every other Trainer feature (augmentation, class weights, early
stopping, periodic checkpointing + resume) composes unchanged — the
step here mirrors ``make_scan_fit``'s per-step semantics (rng folds,
augment key, weighted loss) exactly, so the fitted params match the
replicated trainer to float tolerance feature-for-feature.
``Zero1Trainer`` remains as the thin historical surface and now
delegates to ``Trainer``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from har_tpu.parallel.mesh import (
    create_mesh,
    data_axes,
    data_shard_count,
    linear_data_shard_index,
)


def make_zero1_fit(
    apply_fn,
    optimizer,
    mesh: Mesh,
    params_template,
    augment=None,
    class_weights=None,  # (C,) per-class loss weights
):
    """(fit, init_opt_state) for a ZeRO-1 scanned training run.

    ``fit(params, opt_state, rng, x, y, batch_idx, step0)`` mirrors
    ``trainer.make_scan_fit``'s contract: params/x/y replicated,
    ``batch_idx`` of shape (total_steps, batch) sharded on its batch
    axis; returns (params, opt_state, per-step losses).  ``opt_state``
    comes from ``init_opt_state()``: optimizer state over the padded
    flattened parameter vector, leading axis sharded over the mesh's
    data axes.

    ``augment``/``class_weights`` follow make_scan_fit exactly — same
    per-step rng folds (augment key one fold past dropout's), same
    weighted loss — so a zero1 fit is math-identical to the replicated
    one feature-for-feature.
    """
    flat0, unravel = ravel_pytree(params_template)
    d = int(flat0.size)
    n = data_shard_count(mesh)
    dpad = -(-d // n) * n
    local = dpad // n
    # all_gather accepts the axis-name tuple directly; when the mesh has
    # no data axes n == 1 and the gather is never taken
    axes = data_axes(mesh)

    # one placement rule, used for both the in/out specs and the initial
    # device_put: array leaves shard their leading axis over the data
    # axes, scalar leaves (e.g. Adam's step count) replicate via the
    # matcher's scalar guard — a one-row rule table over the optimizer
    # state (the zero1 entry of the shared sharding layer)
    from har_tpu.parallel.rules import match_partition_rules, zero1_rules

    opt_template = optimizer.init(jnp.zeros((dpad,), flat0.dtype))
    opt_specs = match_partition_rules(zero1_rules(axes), opt_template)

    def init_opt_state():
        return jax.tree.map(
            lambda leaf, spec: jax.device_put(
                jnp.asarray(leaf), NamedSharding(mesh, spec)
            ),
            opt_template,
            opt_specs,
        )

    def local_fit(params, opt_local, rng, x, y, batch_idx, step0):
        shard = linear_data_shard_index(mesh) if n > 1 else 0

        def step(carry, step_and_idx):
            params, opt_local = carry
            step_i, idx = step_and_idx
            xb, yb = x[idx], y[idx]
            step_rng = jax.random.fold_in(
                jax.random.fold_in(rng, step_i), shard
            )
            if augment is not None:
                # same decorrelation convention as make_scan_fit: the
                # augmentation key is one fold past the dropout key
                xb = augment(jax.random.fold_in(step_rng, 1), xb)

            if class_weights is not None:
                wb = class_weights[yb]
            else:
                wb = jnp.ones((yb.shape[0],), jnp.float32)

            def local_sum(p):
                logits = apply_fn(
                    {"params": p}, xb, train=True,
                    rngs={"dropout": step_rng},
                )
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb
                )
                return jnp.sum(ce * wb), jnp.sum(wb)

            (loss_sum, count), grads = jax.value_and_grad(
                local_sum, has_aux=True
            )(params)
            if n > 1:
                loss_sum, count, grads = jax.lax.psum(
                    (loss_sum, count, grads), axes
                )
            grads = jax.tree.map(lambda g: g / count, grads)

            # this shard's contiguous 1/N of the flattened vectors
            gslice = jax.lax.dynamic_slice(
                jnp.pad(ravel_pytree(grads)[0], (0, dpad - d)),
                (shard * local,), (local,),
            )
            pslice = jax.lax.dynamic_slice(
                jnp.pad(ravel_pytree(params)[0], (0, dpad - d)),
                (shard * local,), (local,),
            )
            updates, opt_local = optimizer.update(
                gslice, opt_local, pslice
            )
            pslice = optax.apply_updates(pslice, updates)
            if n > 1:
                # tiled over the data axes in linear-shard order (the
                # same slice-major order linear_data_shard_index uses)
                pfull = jax.lax.all_gather(
                    pslice, axes, tiled=True
                )[:d]
            else:
                pfull = pslice[:d]
            params = unravel(pfull)
            return (params, opt_local), loss_sum / count

        steps = step0 + jnp.arange(batch_idx.shape[0])
        (params, opt_local), losses = jax.lax.scan(
            step, (params, opt_local), (steps, batch_idx)
        )
        return params, opt_local, losses

    rep = P()
    fit = jax.shard_map(
        local_fit,
        mesh=mesh,
        in_specs=(rep, opt_specs, rep, rep, rep, P(None, axes), rep),
        out_specs=(rep, opt_specs, rep),
        check_vma=False,
    )
    return jax.jit(fit, donate_argnums=(0, 1)), init_opt_state


@dataclasses.dataclass
class Zero1Trainer:
    """Scanned trainer with ZeRO-1 optimizer-state sharding.

    Thin historical surface over ``train.Trainer(..., zero1=True)`` —
    the composed path, where augmentation, class weights, early stopping
    and checkpoint/resume all work with the sharded optimizer state.
    Prefer constructing ``Trainer`` directly.
    """

    module: Any
    config: Any = None
    mesh: Mesh | None = None

    def fit(self, x, y, num_classes: int | None = None):
        from har_tpu.train.trainer import Trainer

        trainer = Trainer(
            self.module,
            self.config,
            mesh=self.mesh or create_mesh(dp=-1),
            scan=True,
            zero1=True,
        )
        return trainer.fit(
            np.asarray(x, np.float32),
            np.asarray(y, np.int32),
            num_classes=num_classes,
        )
