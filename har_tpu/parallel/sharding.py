"""Sharding specs + host→device placement helpers.

The reference's data distribution is Spark partitioning rows across
executors (implicit under every action, SURVEY §2c.1).  Here distribution
is declarative: arrays carry a `NamedSharding`, and XLA inserts the
collectives the layout implies.

Training shards through ``shard_batch`` (pad to the dp size + validity
mask).  SERVING shards through ``batch_sharding`` directly: the fleet
engine's ``ShardedScorer`` (har_tpu.serve.dispatch) places each padded
dispatch batch with ``batch_sharding(mesh, ndim=3)`` — rows split over
the data axes, no mask needed because the pad policy
(``serving.pad_shard``: devices × pow2) makes the batch divide the
shard count exactly and padded rows are sliced off at the fetch.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from har_tpu.parallel.mesh import DP_AXIS, data_axes, data_shard_count


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Rows sharded over every data axis (dp, plus dp_dcn on hybrid
    multi-slice meshes), everything else replicated."""
    axes = data_axes(mesh) or (DP_AXIS,)
    return NamedSharding(mesh, P(axes, *([None] * (ndim - 1))))

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(
    arr: np.ndarray, multiple: int, axis: int = 0, fill=0
) -> tuple[np.ndarray, int]:
    """Pad ``arr`` along ``axis`` to a multiple; returns (padded, n_pad).

    Static shapes are mandatory under jit, and the dp axis must divide the
    batch; padding + a validity mask is the XLA-friendly answer to Spark's
    arbitrary last-partition sizes.
    """
    n = arr.shape[axis]
    n_pad = (-n) % multiple
    if n_pad == 0:
        return arr, 0
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, n_pad)
    return np.pad(arr, widths, constant_values=fill), n_pad


def shard_batch(mesh: Mesh, *arrays: np.ndarray) -> tuple:
    """Pad each array's leading dim to the dp size and place it sharded.

    Returns ``(*device_arrays, mask)`` where ``mask`` is 1.0 for real rows
    and 0.0 for padding — consumers weight their reductions by it.
    """
    dp = data_shard_count(mesh)
    out = []
    n = arrays[0].shape[0]
    for a in arrays:
        if a.shape[0] != n:
            raise ValueError("all arrays must share the leading dimension")
        padded, _ = pad_to_multiple(a, dp)
        out.append(
            jax.device_put(padded, batch_sharding(mesh, padded.ndim))
        )
    mask_host, _ = pad_to_multiple(
        np.ones(n, np.float32), dp
    )
    mask = jax.device_put(mask_host, batch_sharding(mesh, 1))
    return (*out, mask)
