"""Tensor parallelism: sharded parameters, XLA-inserted collectives.

The reference has no model large enough to shard (SURVEY §2c.3), but the
framework's neural families (wide MLPs, transformer FFN/attention) are —
so tp is first-class here.  The design is GSPMD, not hand-written
collectives: parameters carry `NamedSharding`s over the mesh's ``tp``
axis, the batch is sharded over ``dp``, and XLA inserts the
all-reduce/all-gather the layout implies (the scaling-book recipe: pick a
mesh, annotate shardings, let the compiler place collectives on ICI).

`dense_alternating_specs` produces the Megatron layout for stacks of
Dense layers: kernels alternately column-parallel ``P(None, tp)`` and
row-parallel ``P(tp, None)``, biases following their kernel — one
all-reduce per pair, activations stay sharded on the hidden dim between
them.  It walks any Flax param tree in deterministic order, so it covers
the MLP and the transformer's qkv/proj + FFN pairs alike.

`make_gspmd_scan_fit` is the tp-aware twin of
har_tpu.train.trainer.make_scan_fit: same whole-run `lax.scan`, but
jit-with-shardings instead of `shard_map`, because tensor parallelism
wants the compiler to split the matmuls themselves.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from har_tpu.parallel.mesh import DP_AXIS, TP_AXIS


def dense_alternating_specs(params, tp_axis: str = TP_AXIS):
    """PartitionSpec pytree: alternate column-/row-parallel 2-D kernels.

    The i-th 2-D ``kernel`` (natural layer order: Dense_0, Dense_1, …,
    Dense_10 after Dense_9) gets ``P(None, tp)`` for even i
    (column-parallel: output dim sharded) and ``P(tp, None)`` for odd i
    (row-parallel: input dim sharded — its input activations are already
    sharded by the previous layer).  A bias directly following a
    column-parallel kernel is ``P(tp)``; everything else (LayerNorm
    scales, small heads, LSTM cells) is replicated.

    Collapsed onto the rule-table layer (`har_tpu.parallel.rules`): the
    hand-built spec walk is now ``alternating_rules`` (the table this
    tree generates, exact-path regex per kernel) resolved by
    ``match_partition_rules`` — the same first-match-wins machinery the
    serving-side `ModelParallelScorer` and the static family tables
    (`DENSE_MLP_RULES`) use.
    """
    from har_tpu.parallel.rules import (
        alternating_rules,
        match_partition_rules,
    )

    return match_partition_rules(
        alternating_rules(params, tp_axis, kernels_only=True), params
    )


def shard_params(params, mesh: Mesh, specs=None):
    """Place a param pytree on the mesh per ``specs`` (default Megatron)."""
    specs = dense_alternating_specs(params) if specs is None else specs
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
    )


def make_gspmd_scan_fit(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    augment: Callable | None = None,
    class_weights=None,  # (C,) per-class loss weights, or None
) -> Callable:
    """fit(params, opt_state, rng, x, y, batch_idx, step0) → (params, opt_state, losses).

    ``step0`` is the global index of the first step (nonzero when a
    checkpointed run executes in chunks).
    Inputs' placements drive the partitioning: params arrive tp-sharded
    (see `shard_params`), x/y replicated, and each gathered batch is
    constrained to ``P(dp)`` — XLA propagates from there and inserts the
    tp all-reduces and the dp gradient reduction itself (no explicit
    psum: the compiler's reduction IS the treeAggregate equivalent).

    ``augment``/``class_weights`` mirror trainer.make_scan_fit: the
    augmentation runs inside the compiled step on the dp-sharded batch,
    and class weighting turns the loss into Σ(ce·w)/Σw — both global
    reductions the compiler places for the sharded layout.

    On hybrid multi-slice meshes the batch constraint covers BOTH data
    axes (``(dp_dcn, dp)``), so every slice works on distinct rows and
    the compiler's gradient reduction crosses DCN once per step.
    """
    from har_tpu.parallel.mesh import data_axes

    cw = None if class_weights is None else jnp.asarray(class_weights)
    batch_spec = P(data_axes(mesh) or DP_AXIS)

    def fit(params, opt_state, rng, x, y, batch_idx, step0):
        def step(carry, step_and_idx):
            params, opt_state = carry
            step_i, idx = step_and_idx
            xb = jax.lax.with_sharding_constraint(
                x[idx], NamedSharding(mesh, batch_spec)
            )
            yb = jax.lax.with_sharding_constraint(
                y[idx], NamedSharding(mesh, batch_spec)
            )
            step_rng = jax.random.fold_in(rng, step_i)
            if augment is not None:
                # same rng decorrelation convention as make_scan_fit
                xb = augment(jax.random.fold_in(step_rng, 1), xb)

            def mean_loss(p):
                logits = apply_fn(
                    {"params": p}, xb, train=True,
                    rngs={"dropout": step_rng},
                )
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb
                )
                if cw is None:
                    return ce.mean()
                wb = cw[yb]
                return (ce * wb).sum() / wb.sum()

            loss, grads = jax.value_and_grad(mean_loss)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        # step0: global step numbering across checkpointed chunks
        steps = step0 + jnp.arange(batch_idx.shape[0])
        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), (steps, batch_idx)
        )
        return params, opt_state, losses

    # placement-driven GSPMD by design (module docstring): params
    # arrive tp-sharded via shard_params, the batch is constrained to
    # P(dp) inside the step, and XLA propagates — declaring
    # in_shardings here would force one layout per call site instead
    # harlint: spec-ok
    return jax.jit(fit, donate_argnums=(0, 1))


def make_gspmd_train_step(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    augment: Callable | None = None,
) -> Callable:
    """Per-batch GSPMD step for the STREAMING trainer path under tp>1.

    step(params, opt_state, rng, x, y, mask) → (params, opt_state, loss).
    Params arrive tp-sharded (`shard_params`); the host feeds each batch
    already dp-sharded (trainer.batch_sharding), and XLA propagates the
    layout — inserting the tp all-reduces and dp gradient reduction —
    exactly as in make_gspmd_scan_fit, one dispatch per batch instead of
    one per run.  The per-row mask doubles as the class-weight carrier,
    like the data-parallel streaming step.
    """

    def step(params, opt_state, rng, x, y, mask):
        if augment is not None:
            # same rng decorrelation convention as the scan paths
            x = augment(jax.random.fold_in(rng, 1), x)

        def mean_loss(p):
            logits = apply_fn(
                {"params": p}, x, train=True, rngs={"dropout": rng}
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            )
            return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)

        loss, grads = jax.value_and_grad(mean_loss)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # same reviewed placement-driven pattern as make_gspmd_scan_fit:
    # input placements (shard_params + trainer.batch_sharding) drive
    # the partitioning
    # harlint: spec-ok
    return jax.jit(step, donate_argnums=(0, 1))


def tp_dim_check(params, specs, tp: int) -> None:
    """Refuse silently-unsharded layouts: every tp-sharded dim must divide."""
    def check(x, s):
        for dim, name in zip(x.shape, tuple(s) + (None,) * x.ndim):
            if name is not None and dim % tp:
                raise ValueError(
                    f"param dim {dim} not divisible by tp={tp} "
                    f"(shape {x.shape}, spec {s})"
                )
    jax.tree.map(check, params, specs)
