"""End-to-end pipeline runner: the reference's `main.py` flow, TPU-native.

Reference flow (SURVEY §1): load CSV → EDA prints → feature pipeline →
70/30 split → {LR, DT, RF} × {plain, 5-fold CV} → evaluation battery →
result.txt + 2 CSVs + hexbin plots.  This module drives the same flow
through the framework's layers from a single RunConfig.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any

import numpy as np

from har_tpu.config import RunConfig
from har_tpu.data.synthetic import synthetic_wisdm
from har_tpu.data.wisdm import (
    WISDM_NUMERIC_COLUMNS,
    load_wisdm,
    numeric_feature_view,
)
from har_tpu.features.wisdm_pipeline import (
    FeatureSet,
    build_wisdm_pipeline,
    make_feature_set,
)
from har_tpu.models.forest import RandomForestClassifier
from har_tpu.models.gbdt import GradientBoostedTreesClassifier
from har_tpu.models.logistic_regression import LogisticRegression
from har_tpu.models.neural_classifier import NeuralClassifier
from har_tpu.models.tree import DecisionTreeClassifier
from har_tpu.ops.metrics import evaluate
from har_tpu.reporting import ModelResult, ReportWriter
from har_tpu.train.trainer import TrainerConfig
from har_tpu.tuning import CrossValidator, param_grid


_ALIASES = {
    "lr": "logistic_regression",
    "dt": "decision_tree",
    "rf": "random_forest",
    "gbt": "gbdt",
}

_CLASSICAL = {
    "logistic_regression": LogisticRegression,
    "decision_tree": DecisionTreeClassifier,
    "random_forest": RandomForestClassifier,
    "gbdt": GradientBoostedTreesClassifier,
}

_NEURAL = ("mlp", "cnn1d", "bilstm", "transformer")
# models that consume (n, T, 3) raw windows, not tabular feature vectors
_RAW_MODELS = ("cnn1d", "bilstm", "transformer")


def effective_synthetic_rows(data) -> int:
    """Row count a synthetic fallback actually generates for this config —
    the single source of truth shared by load_dataset and checkpoint
    provenance metadata."""
    defaults = {"wisdm_raw": 4000, "ucihar": 2000}
    return data.synthetic_rows or defaults.get(data.dataset, 5418)

def _neural_model_fields(name: str) -> set[str]:
    """Attribute names of a neural family's Flax module (they are
    dataclasses), minus flax-internal fields."""
    if name == "transformer":
        from har_tpu.models.transformer import Transformer1D as cls
    else:
        from har_tpu.models.neural import MODEL_REGISTRY

        cls = MODEL_REGISTRY[name]
    if not dataclasses.is_dataclass(cls):
        return set()
    return {
        f.name
        for f in dataclasses.fields(cls)
        if f.name not in ("parent", "name")
    }


def _known_params() -> set[str]:
    """Every hyperparameter name any estimator accepts (classical fields,
    trainer knobs, neural module attributes); a param outside this union
    is a typo, not a cross-model knob, and must fail loudly."""
    known = {
        f.name
        for cls in _CLASSICAL.values()
        for f in dataclasses.fields(cls)
    } | {f.name for f in dataclasses.fields(TrainerConfig)} | {"augment"}
    known.discard("mesh")  # infrastructure field, not a hyperparameter
    for name in _NEURAL:
        known |= _neural_model_fields(name)
    return known


def canonical_model_name(name: str) -> str:
    return _ALIASES.get(name, name)


def build_estimator(name: str, params: dict | None = None, mesh=None):
    name = canonical_model_name(name)
    params = dict(params or {})
    # one params dict serves every model in --models: each estimator
    # keeps only the knobs it has (trainer-only keys and other
    # estimators' keys fall away) — but names NO estimator anywhere
    # accepts are typos and must fail loudly
    unknown = set(params) - _known_params()
    if unknown:
        raise ValueError(
            f"unknown hyperparameter(s) {sorted(unknown)} — not "
            "accepted by any estimator"
        )
    if name in _CLASSICAL:
        cls = _CLASSICAL[name]
        fields = {f.name for f in dataclasses.fields(cls)}
        if params.get("class_weight") is not None and (
            "class_weight" not in fields
        ):
            # shared-knob leniency must not silently train an UNWEIGHTED
            # model when the user asked for weighting (only LR and the
            # neural trainers support it).  Warn rather than raise: one
            # params dict serves every model in a mixed --models run, so
            # aborting here would make `--models mlp dt --class-weight
            # balanced` unreachable.
            import warnings

            warnings.warn(
                f"class_weight is ignored by {name} (supported by "
                "logistic_regression and the neural families); "
                f"{name} trains unweighted",
                UserWarning,
                stacklevel=2,
            )
        # "mesh" is infrastructure, not a hyperparameter: a params-dict
        # mesh would bypass type checks and collide with the mesh arg
        kwargs = {
            k: v
            for k, v in params.items()
            if k in fields and k != "mesh"
        }
        if mesh is not None and "mesh" in fields:
            # classical estimators with a device-parallel sweep (LR's
            # cv_scores shards the grid axis) get the mesh; plain fits
            # ignore it
            kwargs["mesh"] = mesh
        return cls(**kwargs)
    if name in _NEURAL:
        train_keys = {f.name for f in dataclasses.fields(TrainerConfig)}
        cfg = TrainerConfig(
            **{k: params.pop(k) for k in list(params) if k in train_keys}
        )
        augment = params.pop("augment", None)
        # cross-model keys (other estimators' knobs) fall away here just
        # like in the classical branch
        fields = _neural_model_fields(name)
        return NeuralClassifier(
            name,
            config=cfg,
            model_kwargs={k: v for k, v in params.items() if k in fields},
            mesh=mesh,
            augment=augment,
        )
    raise ValueError(f"unknown model {name!r}")


# The reference's LR grid (Main/main.py:202-207); DT/RF grids are empty.
REFERENCE_GRIDS = {
    "logistic_regression": dict(
        reg_param=[0.1, 0.3, 0.5], elastic_net_param=[0.0, 0.1, 0.2]
    ),
}


def load_dataset(config: RunConfig):
    path = config.data.resolved_path()
    if config.data.dataset == "wisdm_raw":
        # the raw tri-axial stream (BASELINE.json configs 3/5): a real
        # WISDM_ar_v1.1_raw.txt via the native parser, or the synthetic
        # class-conditional generator when no path is given
        from har_tpu.data.raw_loader import load_raw_stream, stream_windows
        from har_tpu.data.raw_windows import (
            WindowedDataset,
            synthetic_raw_stream,
        )
        from har_tpu.data.wisdm import ACTIVITIES

        if config.data.path is not None:
            stream = load_raw_stream(config.data.path)
            ds = stream_windows(stream)
            # parser ids are first-appearance order; remap to the
            # canonical WISDM label order when the names line up
            if set(stream.activity_names) <= set(ACTIVITIES):
                remap = np.asarray(
                    [ACTIVITIES.index(n) for n in stream.activity_names],
                    np.int32,
                )
                ds = WindowedDataset(
                    ds.windows, remap[ds.labels], class_names=ACTIVITIES
                )
            # non-canonical names (e.g. WISDM v2 activities) keep the
            # parser's first-appearance ids + names from stream_windows
            return ds
        return synthetic_raw_stream(
            n_windows=effective_synthetic_rows(config.data),
            seed=config.data.seed,
        )
    if config.data.dataset == "synthetic":
        return synthetic_wisdm(
            n_rows=effective_synthetic_rows(config.data),
            seed=config.data.seed,
        )
    if config.data.dataset == "wisdm":
        if path is None:  # reference mount absent → same-shape synthetic
            return synthetic_wisdm(
                n_rows=effective_synthetic_rows(config.data),
                seed=config.data.seed,
            )
        return load_wisdm(path, drop_binned=config.data.drop_binned)
    if config.data.dataset == "ucihar":
        from har_tpu.data.ucihar import load_ucihar, synthetic_ucihar

        if path is None:
            return synthetic_ucihar(
                n_rows=effective_synthetic_rows(config.data),
                seed=config.data.seed,
            )
        return load_ucihar(path)
    raise ValueError(f"unknown dataset {config.data.dataset!r}")


def _feature_mode(config: RunConfig) -> str:
    """Which feature view this config's model trains on."""
    name = canonical_model_name(config.model.name)
    if config.data.dataset == "wisdm_raw":
        # raw-window models consume the windows directly; everything
        # else gets the jitted 43-feature WISDM transform of them
        return "raw" if name in _RAW_MODELS else "raw_features"
    if name in _RAW_MODELS:
        raise ValueError(
            f"{name} trains on raw (T, 3) windows — use "
            "--dataset wisdm_raw (optionally --data-path "
            "WISDM_ar_v1.1_raw.txt), not a tabular dataset "
            f"({config.data.dataset})"
        )
    if config.data.dataset == "ucihar":
        return "ucihar"
    return getattr(config.model, "feature_view", None) or (
        "numeric" if name in ("mlp", "gbdt") else "onehot"
    )


def resolve_split_method(data) -> str:
    """Which split implementation a DataConfig gets.

    "auto" replays the reference's randomSplit bit-for-bit on the tabular
    WISDM dataset (har_tpu.data.spark_split; 3,793/1,625 for seed 2018) and
    falls back to the plain Bernoulli draw for datasets whose rows don't
    carry the WISDM sort columns.
    """
    method = getattr(data, "split_method", "auto")
    if method == "auto":
        return "spark" if data.dataset == "wisdm" else "bernoulli"
    if method not in ("spark", "bernoulli"):
        raise ValueError(f"unknown split_method {method!r}")
    if method == "spark" and data.dataset != "wisdm":
        raise ValueError(
            "split_method='spark' replays the reference's WISDM randomSplit "
            f"and needs the WISDM sort columns; dataset {data.dataset!r} "
            "doesn't carry them"
        )
    return method


def derive_split(
    full: FeatureSet, table, data
) -> tuple[FeatureSet, FeatureSet]:
    """THE train/test derivation for tabular WISDM views.

    Every path that scores a model (run, sweep, checkpoint evaluate and
    predict) must go through here or FeatureSet.train_test, or risk
    scoring on different rows than training held out.
    """
    if resolve_split_method(data) == "spark":
        from har_tpu.data.spark_split import (
            assemble_rows,
            spark_split_indices,
        )
        from har_tpu.models.mllib_exact import DeferredExactDesign

        asm = assemble_rows(table)
        train_idx, test_idx = spark_split_indices(
            table,
            [data.train_fraction, 1.0 - data.train_fraction],
            data.seed,
            rows=asm,
        )
        # float64 design for the bit-exact MLlib replay estimators,
        # deferred: assemble_rows was already paid for the split itself,
        # and the CSR packing happens only if an exact estimator runs
        shared: dict = {}
        return (
            dataclasses.replace(
                full.take(train_idx),
                rows=train_idx,
                exact=DeferredExactDesign(shared, asm, train_idx),
            ),
            dataclasses.replace(
                full.take(test_idx),
                rows=test_idx,
                exact=DeferredExactDesign(shared, asm, test_idx),
            ),
        )
    return full.train_test(data.train_fraction, data.seed)


def featurize(config: RunConfig, table) -> tuple[FeatureSet, FeatureSet, Any]:
    """Fit the one-hot pipeline (reference parity) or the numeric view.

    UCI-HAR tables are already numeric (561 FEAT_* columns) and bypass the
    WISDM-specific views entirely.
    """
    mode = _feature_mode(config)  # raises for impossible model/dataset
    if mode == "ucihar":
        from har_tpu.data.ucihar import ucihar_feature_set

        full = ucihar_feature_set(table)
        train, test = full.train_test(
            config.data.train_fraction, config.data.seed
        )
        return train, test, None
    if mode in ("raw", "raw_features"):
        # table is a WindowedDataset here (load_dataset, wisdm_raw)
        if mode == "raw":
            x = np.asarray(table.windows, np.float32)
        else:
            from har_tpu.features.raw_features import extract_features

            x = np.asarray(extract_features(table.windows), np.float32)
        full = FeatureSet(
            features=x,
            label=np.asarray(table.labels, np.int32),
            class_names=(
                tuple(table.class_names) if table.class_names else None
            ),
        )
        train, test = full.train_test(
            config.data.train_fraction, config.data.seed
        )
        return train, test, None
    if mode == "numeric":
        from har_tpu.data.wisdm import BINNED_COLUMNS
        from har_tpu.features.string_indexer import StringIndexer

        # GBDT uses the 30 histogram-bin columns when the loader kept them
        # (its best-accuracy view); the neural models keep the stable
        # 13-dim view so checkpoints don't silently change input width.
        has_bins = canonical_model_name(config.model.name) == "gbdt" and all(
            c in table.column_names for c in BINNED_COLUMNS
        )
        x, _ = numeric_feature_view(table, include_binned=has_bins)
        indexer = StringIndexer("ACTIVITY", "label").fit(table)
        y = np.asarray(indexer.transform(table)["label"], np.int32)
        uid = table["UID"] if "UID" in table.column_names else None
        full = FeatureSet(
            features=x, label=y, uid=uid, class_names=indexer.vocab
        )
        pipe_model = None
    else:
        pipeline = build_wisdm_pipeline()
        pipe_model = pipeline.fit(table)
        label_vocab = next(
            (
                s.vocab
                for s in pipe_model.stages
                if getattr(s, "output_col", None) == "label"
            ),
            None,
        )
        full = make_feature_set(
            pipe_model.transform(table), class_names=label_vocab
        )
    train, test = derive_split(full, table, config.data)
    return train, test, pipe_model


def _views_for(models, config: RunConfig, table, timer=None):
    """Resolve each model's feature view, featurizing once per view.

    Raises before any featurization if some model can't run on this
    dataset.  Returns ``(modes, view_cache)`` — ``view_cache[mode]`` is
    the (train, test, fitted_pipeline_or_None) triple every model with
    that mode trains on.  Shared by run() and sweep() so the two entry
    points can never drift onto different views for the same model.
    """
    model_cfgs = {
        name: dataclasses.replace(
            config, model=dataclasses.replace(config.model, name=name)
        )
        for name in models
    }
    modes = {name: _feature_mode(cfg) for name, cfg in model_cfgs.items()}
    view_cache: dict[str, tuple] = {}
    for name in models:
        if modes[name] not in view_cache:
            if timer is not None:
                with timer("featurize"):
                    view = featurize(model_cfgs[name], table)
            else:
                view = featurize(model_cfgs[name], table)
            view_cache[modes[name]] = view
    return modes, view_cache


@dataclasses.dataclass
class RunOutcome:
    report_paths: dict[str, str]
    results: list[ModelResult]

    @property
    def accuracies(self) -> dict[str, float]:
        return {
            r.name: float(r.metrics["accuracy"]) for r in self.results
        }


# (estimator class, pretty name) per classical family, for the report's
# Spark-style model lines (result.txt:141,186,231,276)
_SPARK_NAMES = {
    "logistic_regression": ("LogisticRegression", "Logistic Regression"),
    "decision_tree": ("DecisionTreeClassifier", "Decision Tree"),
    "random_forest": ("RandomForestClassifier", "Random Forest"),
    "gbdt": ("GBTClassifier", "Gradient Boosted Trees"),
}


def _spark_display_name(name: str, model, is_cv: bool) -> str | None:
    """The model line Spark prints atop each block: estimator uid for LR,
    fitted-model reprs for trees (result.txt:141,231,276), and
    "CrossValidatorModel_<uid> for <family>" for CV (result.txt:186).
    Spark's uid suffix is 20 random hex chars; ours is a deterministic
    hash of the job name.  Neural families keep their own names."""
    import hashlib

    base = name[: -len("_cv")] if name.endswith("_cv") else name
    entry = _SPARK_NAMES.get(base)
    if entry is None:
        return None
    est_cls, pretty = entry
    uid = hashlib.sha1(name.encode()).hexdigest()[:20]
    if is_cv:
        return f"CrossValidatorModel_{uid} for {pretty}"
    if base == "decision_tree":
        return (
            f"DecisionTreeClassificationModel (uid={est_cls}_{uid}) of "
            f"depth {model.tree.max_depth} with {model.num_nodes} nodes"
        )
    if base == "random_forest":
        return (
            f"RandomForestClassificationModel (uid={est_cls}_{uid}) "
            f"with {model.num_trees} trees"
        )
    if base == "gbdt":
        return f"GBTClassificationModel (uid={est_cls}_{uid})"
    return f"{est_cls}_{uid}"


def _fit_eval(est, name, train, test, report, is_cv=False, timer=None):
    from har_tpu.utils.profiling import StepTimer

    timer = timer if timer is not None else StepTimer()
    with timer(f"{name}_fit") as fit_sec:
        model = est.fit(train)
    train_time = fit_sec.seconds
    with timer(f"{name}_transform") as tf_sec:
        preds = model.transform(test)
    test_time = tf_sec.seconds
    metrics = evaluate(test.label, preds.raw, model.num_classes)
    result = ModelResult(
        name=name,
        metrics=metrics,
        train_time_s=train_time,
        test_time_s=test_time,
        is_cv=is_cv,
        display_name=_spark_display_name(name, model, is_cv),
    )
    report.model_block(
        result, sample_text=report.prediction_sample(test, preds)
    )
    return result, model


def sweep(
    config: RunConfig,
    models=None,
    fractions=(0.7, 0.8, 0.9),
    with_cv=True,
) -> list[dict]:
    """Split-ratio sweep: the paper's Table 1/2 experiment as one command.

    The paper (reference Paper/, §4 Tables 1-2) re-runs the pipeline at
    70-30 / 80-20 / 90-10 splits by hand-editing the script; here it's a
    config sweep.  Returns one row per (split, model) with timings and
    test metrics, writes ``sweep.csv`` + a Spark-`show()`-style table to
    ``sweep.txt`` under ``config.output_dir``.

    CV rows are produced only for estimators with a non-empty reference
    grid (LR — Main/main.py:202-207), matching the paper's "LR with
    cross fold" rows.
    """
    import csv
    import os

    from har_tpu.reporting.ascii_table import show

    models = [
        canonical_model_name(m)
        for m in (
            models
            or ["logistic_regression", "decision_tree", "random_forest"]
        )
    ]
    if not models or not fractions:
        raise ValueError("sweep needs at least one model and one fraction")
    table = load_dataset(config)
    mesh = _mesh_from_config(config)
    rows: list[dict] = []
    for frac in fractions:
        cfg = dataclasses.replace(
            config,
            data=dataclasses.replace(config.data, train_fraction=frac),
        )
        # each model trains on the same view `run()` would give it,
        # computed once per distinct view per split
        modes, view_cache = _views_for(models, cfg, table)
        split_name = f"{round(frac * 100)}-{round((1 - frac) * 100)}"
        for name in models:
            train, test = view_cache[modes[name]][:2]
            est = build_estimator(name, config.model.params, mesh=mesh)
            jobs = [(name, est)]
            if with_cv and name in REFERENCE_GRIDS:
                jobs.append(
                    (
                        f"{name}_cv",
                        CrossValidator(
                            estimator=est,
                            grid=param_grid(**REFERENCE_GRIDS[name]),
                            num_folds=5,
                            selection_metric=(
                                config.tuning.selection_metric
                                if config.tuning
                                else "accuracy"
                            ),
                            seed=config.data.seed,
                        ),
                    )
                )
            for job_name, job_est in jobs:
                t0 = time.perf_counter()
                model = job_est.fit(train)
                train_time = time.perf_counter() - t0
                t0 = time.perf_counter()
                preds = model.transform(test)
                test_time = time.perf_counter() - t0
                metrics = evaluate(test.label, preds.raw, model.num_classes)
                rows.append(
                    {
                        "split": split_name,
                        "model": job_name,
                        "n_train": len(train),
                        "n_test": len(test),
                        "train_time_s": round(train_time, 3),
                        "test_time_s": round(test_time, 3),
                        "accuracy": round(float(metrics["accuracy"]), 6),
                        "f1": round(float(metrics["f1"]), 6),
                    }
                )

    os.makedirs(config.output_dir, exist_ok=True)
    columns = list(rows[0].keys())
    csv_path = os.path.join(config.output_dir, "sweep.csv")
    with open(csv_path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=columns)
        writer.writeheader()
        writer.writerows(rows)
    txt = show(columns, [[r[c] for c in columns] for r in rows],
               max_rows=None)
    with open(os.path.join(config.output_dir, "sweep.txt"), "w") as f:
        f.write(txt)
    print(txt, end="")
    return rows


def _mesh_from_config(config: RunConfig):
    """Build the SPMD mesh the config asks for (None → single device).

    MeshConfig.dp = -1 means "all available devices"; dp×tp == 1 returns
    None so single-chip runs skip the sharding machinery entirely.
    Classical estimators ignore the mesh (their fits are single compiled
    programs); neural trainers shard batches over dp and params over tp.
    """
    import jax

    devices = jax.devices()
    dp, tp = config.mesh.shape(len(devices))
    if dp * tp == 1:
        return None
    if dp * tp > len(devices):
        raise ValueError(
            f"mesh dp={dp} x tp={tp} needs {dp * tp} devices but only "
            f"{len(devices)} are available"
        )
    from har_tpu.parallel import create_mesh

    if dp * tp < len(devices) and jax.process_count() > 1:
        # a subset of global devices can exclude another process's chips
        # entirely — its dispatches would have nothing to run on; multi-
        # host meshes must span every device
        raise ValueError(
            f"mesh dp={dp} x tp={tp} covers {dp * tp} of "
            f"{len(devices)} global devices; in a multi-host run the "
            "mesh must use all of them (set dp=-1 or dp*tp == device "
            "count)"
        )
    # single-process: an explicit dp/tp smaller than the host's device
    # count uses the first dp*tp devices
    return create_mesh(dp=dp, tp=tp, devices=devices[: dp * tp])


def _save_fitted(
    base_dir: str, job_name: str, model, est, config: RunConfig, pipe_model,
    input_shape: tuple | None = None,
):
    """Persist one fitted model under ``base_dir/job_name``.

    Neural models go through the orbax path; classical families are
    npz+JSON, bundling the fitted one-hot pipeline's vocabularies when the
    model was trained on it (so the artifact featurizes raw tables).
    """
    from har_tpu.checkpoint import save_classical_model, save_model
    from har_tpu.models.neural_classifier import NeuralClassifierModel

    path = os.path.join(base_dir, job_name)
    synthetic_rows = None
    if config.data.resolved_path() is None:
        # record the EFFECTIVE row count (load_dataset's defaults), so
        # evaluate_checkpoint's provenance guard fires even for runs that
        # never set synthetic_rows explicitly
        synthetic_rows = effective_synthetic_rows(config.data)
    split_method = resolve_split_method(config.data)
    if isinstance(model, NeuralClassifierModel):
        return save_model(
            path,
            model,
            est.model_name,
            dict(est.model_kwargs),
            dataset=config.data.dataset,
            synthetic_rows=synthetic_rows,
            drop_binned=config.data.drop_binned,
            split_method=split_method,
            input_shape=input_shape,
            split_seed=config.data.seed,
            train_fraction=config.data.train_fraction,
        )
    return save_classical_model(
        path,
        model,
        dataset=config.data.dataset,
        synthetic_rows=synthetic_rows,
        drop_binned=config.data.drop_binned,
        split_method=split_method,
        pipeline=pipe_model,
        split_seed=config.data.seed,
        train_fraction=config.data.train_fraction,
    )


def run(
    config: RunConfig,
    models=None,
    with_cv=True,
    with_eda=False,
    save_models_dir: str | None = None,
) -> RunOutcome:
    """The whole reference pipeline: EDA → features → models → artifacts."""
    from har_tpu.utils.profiling import StepTimer, write_timing_csv

    timer = StepTimer()
    with timer("load"):
        table = load_dataset(config)
    is_raw = not hasattr(table, "column_names")  # WindowedDataset
    report = ReportWriter(config.output_dir)
    report.line("Loading Data Set...")
    if is_raw:
        report.line(
            f"Raw windows: {tuple(table.windows.shape)} "
            f"({table.windows.shape[1]} steps, tri-axial)"
        )
        names = table.class_names or tuple(
            str(i) for i in range(int(table.labels.max()) + 1)
        )
        report.class_counts(
            [names[i] for i in np.asarray(table.labels)]
        )
    else:
        report.schema(table)
        report.sample(table)
        if "ACTIVITY" in table.column_names:
            report.class_counts(table["ACTIVITY"])
        report.summary(table)

    models = [
        canonical_model_name(m)
        for m in (
            models
            or ["logistic_regression", "decision_tree", "random_forest"]
        )
    ]
    # resolve every model's view up front (raises before any training if
    # a model can't run on this dataset), featurizing each view once
    modes, view_cache = _views_for(models, config, table, timer=timer)
    first_train, first_test = view_cache[modes[models[0]]][:2]
    # per-class display names come from the SAME indexer fit that
    # produced the labels (carried on the FeatureSet), so the report can
    # never mislabel classes
    report.class_names = (
        list(first_train.class_names) if first_train.class_names else None
    )
    # MODELING PIPELINE + sample/table blocks (result.txt:59-138) — the
    # one-hot view's transformed frame; with the spark-exact split the
    # shown train/test rows equal the reference's.  The split sets carry
    # their original-row provenance, so the full design matrix is
    # reassembled from them (no second pipeline transform).
    oh_feats = oh_labels = None
    if not is_raw and "onehot" in view_cache:
        oh_train, oh_test, oh_pipe = view_cache["onehot"]
        if (
            oh_pipe is not None
            and oh_train.rows is not None
            and oh_test.rows is not None
        ):
            report.pipeline_schema(table)
            n_rows = len(table)
            d = oh_train.num_features
            oh_feats = np.empty((n_rows, d), np.float32)
            oh_labels = np.empty((n_rows,), np.float64)
            for part in (oh_train, oh_test):
                oh_feats[part.rows] = part.features
                oh_labels[part.rows] = part.label
            report.sample_feature_data(table, oh_labels, oh_feats)
    report.split_counts(len(first_train), len(first_test))
    if oh_feats is not None:
        report.split_sample_tables(
            table, oh_feats, oh_labels, oh_train.rows, oh_test.rows
        )

    mesh = _mesh_from_config(config)
    results = []
    for name in models:
        train, test, pipe_model = view_cache[modes[name]]
        est = build_estimator(name, config.model.params, mesh=mesh)
        result, model = _fit_eval(est, name, train, test, report, timer=timer)
        results.append(result)
        if save_models_dir:
            _save_fitted(
                save_models_dir, name, model, est, config, pipe_model,
                input_shape=np.asarray(train.features).shape[1:],
            )
        if with_cv:
            tuning = config.tuning
            grid_spec = (
                dict(tuning.grid)
                if tuning and tuning.grid
                else REFERENCE_GRIDS.get(name, {})
            )
            metric = tuning.selection_metric if tuning else "accuracy"
            cv = CrossValidator(
                estimator=est,
                grid=param_grid(**grid_spec),
                num_folds=tuning.num_folds if tuning else 5,
                selection_metric=metric,
                seed=config.data.seed,
            )
            cv_result, cv_model = _fit_eval(
                cv, f"{name}_cv", train, test, report,
                is_cv=True, timer=timer,
            )
            results.append(cv_result)
            if save_models_dir:
                # the refit-best model is of the same family as the plain
                # fit; save with the TUNED estimator so neural metadata
                # (model_kwargs) describes the refit architecture
                tuned = (
                    est.copy_with(**cv_model.best_params)
                    if cv_model.best_params
                    else est
                )
                _save_fitted(
                    save_models_dir, f"{name}_cv", cv_model.best_model,
                    tuned, config, pipe_model,
                    input_shape=np.asarray(train.features).shape[1:],
                )

    if with_eda and not is_raw:
        from har_tpu.reporting.eda import save_eda_plots

        numeric = [c for c in WISDM_NUMERIC_COLUMNS if c in table.column_names]
        save_eda_plots(table, numeric, config.output_dir + "/plot")

    paths = report.save()
    # the reference's Graph.xlsx role: 8 metric charts over the two CSVs
    from har_tpu.reporting.charts import save_metric_charts

    charts = save_metric_charts(
        paths.get("csv"), paths.get("cv_csv"), config.output_dir
    )
    if charts:
        paths["charts"] = os.path.dirname(charts[0])
    paths["timing"] = write_timing_csv(
        os.path.join(config.output_dir, "timing.csv"), timer
    )
    return RunOutcome(report_paths=paths, results=results)
