"""Shadow evaluation: score a candidate model on mirrored live traffic
before letting it serve anyone.

A retrained candidate's held-out accuracy says nothing about the live
distribution that triggered the retrain — the honest test is the live
traffic itself.  ``ShadowEvaluator`` is ``FleetServer``'s dispatch tap
(``set_dispatch_tap``): after each batch's events are finalized, it
receives the unpadded windows and the incumbent's probabilities,
deterministically samples a BOUNDED fraction of batches (never the
serving critical path — per-event latencies are recorded before the tap
runs), scores the candidate on the mirror, and accumulates:

  - agreement: argmax match rate candidate-vs-incumbent, measured on
    TRUSTED traffic only (``exclude_sessions`` — the drifted sessions
    that triggered the retrain).  On drifted traffic the incumbent is
    the suspect, so disagreement there is the candidate doing its job;
    on in-distribution traffic the incumbent is the ground reference,
    so disagreement there is regression.  Without the exclusion a
    drift-correcting candidate could never pass an agreement gate —
    the exact failure mode the loop exists to fix;
  - mean |Δp|: probability-level divergence over ALL mirrored windows
    (drifted included — a candidate can agree on argmax while moving
    every confidence; the drifted-side movement is worth seeing).
    CAVEAT: when the incumbent serves FUSED, the tap's incumbent
    probabilities are the compact decision-confidence surrogate
    (serve.dispatch.compact_probs — exact at the decision label,
    uniform elsewhere), so Δp then measures against the surrogate and
    overstates off-label movement.  The agreement gate — the actual
    promotion criterion — compares argmaxes and is exact either way;
  - candidate latency per mirrored batch — a candidate that is too slow
    to serve must fail the gate BEFORE the swap, not after.

``gates()`` is the promotion verdict: enough trusted evidence,
agreement above threshold, latency within budget.  The engine's
``stats.shadow_*`` counters and ``shadow_ms`` histogram carry the same
evidence into every stats snapshot.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class ShadowConfig:
    """Sampling bound + promotion gates."""

    # score every Nth dispatched batch (the bounded mirror fraction:
    # 1/sample_every of dispatches pay a shadow scoring)
    sample_every: int = 2
    # promotion gates
    min_windows: int = 64  # TRUSTED-window evidence floor
    min_agreement: float = 0.98  # argmax match floor on trusted traffic
    # candidate mean batch latency must stay within this factor of the
    # incumbent's observed mean dispatch latency (None disables —
    # host-stub incumbents measure microseconds that no real model meets)
    max_latency_factor: float | None = None
    # initial scored batches EXCLUDED from the latency sample: the
    # candidate's first mirrored batch pays its jit compilation, which
    # is deployment cadence, not serving speed — a latency gate that
    # reads the compile as serving would reject every jitted candidate
    # (the int8 promotion path gates on exactly this sample).  The
    # batches still count toward agreement/Δp evidence.
    latency_warmup: int = 1

    def __post_init__(self):
        if self.sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        if self.latency_warmup < 0:
            raise ValueError("latency_warmup must be >= 0")
        if self.min_windows < 1:
            # 0 would let gates() pass with NO evidence at all (no
            # agreement, no latency) and promote an unscored candidate
            raise ValueError("min_windows must be >= 1")
        if not (0.0 <= self.min_agreement <= 1.0):
            raise ValueError("min_agreement must be in [0, 1]")


class ShadowEvaluator:
    """Accumulating candidate-vs-incumbent comparison over mirrored
    dispatch batches.  Install with ``server.set_dispatch_tap(shadow)``;
    the ``__call__`` signature is the tap contract."""

    def __init__(
        self,
        candidate,
        config: ShadowConfig | None = None,
        *,
        exclude_sessions=None,
        clock: Callable[[], float] | None = None,
    ):
        self.candidate = candidate
        self.config = config or ShadowConfig()
        # the DRIFTED sessions behind the retrain: their windows are
        # scored (Δp visibility) but excluded from the agreement gate —
        # the incumbent is not a trustworthy reference on them
        self.exclude_sessions = (
            frozenset() if exclude_sessions is None
            else frozenset(exclude_sessions)
        )
        self._clock = clock or time.perf_counter
        self._calls = 0
        self.n_batches = 0
        self.n_windows = 0  # trusted (gate-counted) windows
        self.n_windows_excluded = 0  # drifted-session windows scored
        self.n_agree = 0
        self._abs_dp_sum = 0.0
        self._abs_dp_n = 0
        self._cand_ms: list[float] = []
        self._incumbent_ms: float | None = None  # latest running mean

    # ------------------------------------------------------- the tap

    def __call__(
        self, session_ids: Sequence, windows: np.ndarray,
        incumbent_probs: np.ndarray,
    ) -> bool:
        """Score a mirrored batch when the sampler selects it.  Returns
        True when scored (the engine then records shadow accounting)."""
        self._calls += 1
        if (self._calls - 1) % self.config.sample_every:
            return False
        from har_tpu.serving import pad_pow2

        k = len(windows)
        # THE shared power-of-two padding policy (serving.pad_pow2): a
        # jitted candidate reuses the incumbent's program-shape budget
        # instead of compiling one program per tail-batch size (and the
        # latency sample measures serving, not compilation cadence)
        windows = pad_pow2(windows)
        t0 = self._clock()
        preds = self.candidate.transform(windows)
        cand = np.asarray(preds.probability[:k], np.float64)
        if self.n_batches >= self.config.latency_warmup:
            self._cand_ms.append((self._clock() - t0) * 1e3)
        inc = np.asarray(incumbent_probs, np.float64)
        self.n_batches += 1
        trusted = np.asarray(
            [sid not in self.exclude_sessions for sid in session_ids],
            bool,
        )
        self.n_windows += int(trusted.sum())
        self.n_windows_excluded += int((~trusted).sum())
        self.n_agree += int(
            (
                cand[trusted].argmax(axis=-1)
                == inc[trusted].argmax(axis=-1)
            ).sum()
        )
        self._abs_dp_sum += float(np.abs(cand - inc).sum())
        self._abs_dp_n += cand.size
        return True

    def set_incumbent_ms(self, mean_ms: float) -> None:
        """THE entry point for the latency-gate baseline: replace it
        with the incumbent's current running mean (AdaptationEngine
        feeds FleetStats.dispatch's mean each step)."""
        self._incumbent_ms = float(mean_ms)

    # ------------------------------------------------------ verdicts

    @property
    def agreement(self) -> float | None:
        if not self.n_windows:
            return None
        return self.n_agree / self.n_windows

    def report(self) -> dict:
        """JSON-ready evidence summary."""
        return {
            "batches_scored": self.n_batches,
            "windows_scored": self.n_windows,
            "windows_excluded": self.n_windows_excluded,
            "agreement": (
                None if self.agreement is None else round(self.agreement, 4)
            ),
            "mean_abs_prob_delta": (
                round(self._abs_dp_sum / self._abs_dp_n, 6)
                if self._abs_dp_n
                else None
            ),
            "candidate_mean_batch_ms": (
                round(float(np.mean(self._cand_ms)), 3)
                if self._cand_ms
                else None
            ),
            "incumbent_mean_batch_ms": (
                None
                if self._incumbent_ms is None
                else round(self._incumbent_ms, 3)
            ),
        }

    def gates(self) -> dict:
        """The promotion verdict: {passed, reasons, **report}."""
        cfg = self.config
        reasons: list[str] = []
        if self.n_windows < cfg.min_windows:
            reasons.append(
                f"insufficient evidence: {self.n_windows} trusted "
                f"shadow-scored windows < min_windows={cfg.min_windows}"
            )
        agr = self.agreement
        if agr is not None and agr < cfg.min_agreement:
            reasons.append(
                f"agreement {agr:.4f} < min_agreement="
                f"{cfg.min_agreement}"
            )
        if cfg.max_latency_factor is not None:
            if not self._cand_ms:
                # a configured latency gate may NEVER pass on zero
                # latency evidence: with latency_warmup excluding the
                # compile batch, the first mirrored batch alone could
                # otherwise satisfy min_windows and promote a slow
                # candidate entirely unmeasured
                reasons.append(
                    "no post-warmup latency evidence yet "
                    f"(latency_warmup={cfg.latency_warmup}) — the "
                    "max_latency_factor gate needs a measured batch"
                )
            elif self._incumbent_ms is not None:
                cand = float(np.mean(self._cand_ms))
                inc = self._incumbent_ms
                if cand > cfg.max_latency_factor * inc:
                    reasons.append(
                        f"candidate batch latency {cand:.3f}ms > "
                        f"{cfg.max_latency_factor}x incumbent "
                        f"{inc:.3f}ms"
                    )
        out = {"passed": not reasons, "reasons": reasons}
        out.update(self.report())
        return out
