"""Adaptation-loop smoke — the release gate's closed-loop check.

``adapt_smoke()`` runs the WHOLE lifecycle in a couple of seconds on the
CPU mesh, deterministically (FakeClock, seeded streams, training-free
models): a fleet with per-session drift monitors serves in-distribution
traffic, half the fleet's streams then shift (the re-mounted-sensor
scenario at population scale), the trigger escalates, a stub retrainer
produces a candidate, the candidate shadow-scores mirrored live batches,
gates pass, the engine hot-swaps at a dispatch boundary, and probation
closes clean — with ZERO dropped windows and the accounting invariant
(including per-version attribution) intact end to end.

``scripts/release_gate.py`` runs it after a green suite and stamps
``{swaps, rollbacks, shadow_agreement}`` into ``artifacts/test_gate.json``
— the adaptation counterpart of the fleet SLO verdict: generated from a
run, never typed.
"""

from __future__ import annotations

import numpy as np

from har_tpu.adapt.shadow import ShadowConfig
from har_tpu.adapt.swap import AdaptationConfig, AdaptationEngine
from har_tpu.adapt.trigger import TriggerConfig
from har_tpu.adapt.registry import ModelRegistry
from har_tpu.monitoring import DriftMonitor
from har_tpu.serve import (
    AnalyticDemoModel,
    FakeClock,
    FleetConfig,
    FleetServer,
    synthetic_sessions,
)


def adapt_smoke(
    sessions: int = 12,
    *,
    drift_fraction: float = 0.5,
    rounds: int = 12,
    seed: int = 0,
    registry_root: str | None = None,
) -> dict:
    """One JSON-ready verdict for the drift→retrain→shadow→swap loop.

    ``registry_root=None`` keeps the registry in a temp dir that is
    removed afterwards (the gate wants the verdict, not the artifacts).
    """
    import shutil
    import tempfile

    clock = FakeClock()
    model = AnalyticDemoModel()
    recordings, _ = synthetic_sessions(
        sessions, windows_per_session=rounds, seed=seed
    )
    # population reference stats from the clean pool; the drifted half
    # then re-mounts: +25 offset on every axis, way past z=3
    pool = np.concatenate(recordings)
    ref_mean, ref_std = pool.mean(axis=0), pool.std(axis=0)
    n_drift = int(sessions * drift_fraction)
    server = FleetServer(
        model,
        window=200,
        hop=200,
        smoothing="ema",
        config=FleetConfig(max_sessions=sessions, max_delay_ms=0.0),
        clock=clock,
    )
    for i in range(sessions):
        server.add_session(
            i,
            monitor=DriftMonitor(
                ref_mean, ref_std, halflife=100.0, patience=2
            ),
        )
    tmp = None
    if registry_root is None:
        tmp = registry_root = tempfile.mkdtemp(prefix="har_adapt_smoke_")
    try:
        registry = ModelRegistry(registry_root, clock=clock)
        retrains = {"n": 0}

        def retrainer(job):
            # stub retrain: deterministic same-family refit — numerics
            # identical to the incumbent, so shadow agreement is exact
            # and the smoke's swap is provably decision-neutral
            retrains["n"] += 1
            assert job.replay is not None and len(job.replay) > 0
            return AnalyticDemoModel()

        engine = AdaptationEngine(
            server,
            registry,
            retrainer,
            config=AdaptationConfig(probation_dispatches=2),
            trigger_config=TriggerConfig(
                min_sessions=max(2, n_drift // 2),
                window_s=1e9,
                cooldown_s=1e9,
                recovery_patience=2,
            ),
            shadow_config=ShadowConfig(sample_every=1, min_windows=8),
            clock=clock,
        )

        # round-robin delivery: one 200-sample window per session per
        # round; the drifted half shifts from round 2 on
        cursors = [0] * sessions
        for rnd in range(rounds):
            for i in range(sessions):
                rec = recordings[i]
                chunk = rec[cursors[i] : cursors[i] + 200]
                cursors[i] += 200
                if not len(chunk):
                    continue
                if i < n_drift and rnd >= 2:
                    chunk = chunk + 25.0
                server.push(i, chunk)
            server.poll(force=True)
            engine.step()
            clock.advance(1.0)
        server.flush()
        engine.step()

        snap = server.stats_snapshot()
        acct = snap["accounting"]
        status = engine.status()
        shadow_agreement = None
        for entry in engine.log:
            if entry["event"] == "swapped":
                shadow_agreement = entry["shadow"]["agreement"]
        ok = bool(
            status["swaps"] >= 1
            and status["rollbacks"] == 0
            and retrains["n"] >= 1
            and acct["dropped"] == 0
            and acct["pending"] == 0
            and acct["balanced"]
            and shadow_agreement is not None
            and shadow_agreement >= 0.98
        )
        return {
            "ok": ok,
            "sessions": sessions,
            "drifted_sessions": n_drift,
            "windows": acct["enqueued"],
            "dropped": acct["dropped"],
            "accounting_balanced": bool(
                acct["balanced"] and acct["pending"] == 0
            ),
            "retrains": retrains["n"],
            "swaps": status["swaps"],
            "rollbacks": status["rollbacks"],
            "shadow_agreement": shadow_agreement,
            "serving_version": status["serving_version"],
            "state": status["state"],
            "scored_by_version": snap["scored_by_version"],
        }
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    import json

    print(json.dumps(adapt_smoke()))
