"""Versioned model registry: the lineage store the adaptation loop
promotes into and rolls back from.

``checkpoint.save_model`` / ``save_classical_model`` persist ONE model;
a drift-adaptive fleet needs the family tree: which artifact is serving,
what it was trained on, what it descended from, and what to fall back to
when a promotion regresses.  This registry is that — a plain directory
(no database, inspectable with ``ls`` and ``cat``):

    root/
      versions/v0000001/          one artifact per version: whatever the
        ...                         caller's saver wrote (a neural or
        registry.json               classical checkpoint dir, usually)
      versions/v0000002/
      CURRENT                     atomic pointer (symlink, or a text
                                    file where symlinks don't exist)
      NEXT_ID                     monotone id counter — ids never reuse,
                                    even after prune()
      promotions.jsonl            append-only promote/rollback log: the
                                    evidence trail, and what rollback()
                                    walks to find the prior incumbent

Version ids are MONOTONE (a pruned v3 never comes back as a different
model), ``parent_sha256`` chains each version to the artifact bytes of
the incumbent it was trained to replace, and ``data_fingerprint``
records what it was trained on — so "which windows produced the model
that served Tuesday" is answerable from the directory alone.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import time
from typing import Callable, Iterable

# the shared fsync discipline (har_tpu.utils.durable): a crash after a
# bare os.replace could surface an empty/old CURRENT or NEXT_ID, and an
# un-synced promotions.jsonl entry would leave rollback() blind to the
# transition it is supposed to walk back
from har_tpu.utils.durable import atomic_write as _atomic_write
from har_tpu.utils.durable import durable_append as _durable_append
from har_tpu.utils.durable import fsync_dir as _fsync_dir

_VERSIONS = "versions"
_CURRENT = "CURRENT"
_NEXT_ID = "NEXT_ID"
_LOG = "promotions.jsonl"
_META = "registry.json"


def data_fingerprint(*arrays) -> str:
    """sha256 over the shapes + bytes of the training arrays — the
    "what was this trained on" stamp.  Order-sensitive by design: the
    same windows in a different order are a different training run."""
    import numpy as np

    h = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _dir_sha256(path: str) -> str:
    """Deterministic digest of a version dir's artifact bytes (the
    registry's own metadata file excluded — it references this hash)."""
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(path)):
        dirnames.sort()
        for name in sorted(filenames):
            if dirpath == path and name == _META:
                continue
            full = os.path.join(dirpath, name)
            h.update(os.path.relpath(full, path).encode())
            with open(full, "rb") as f:
                for block in iter(lambda: f.read(1 << 20), b""):
                    h.update(block)
    return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class ModelVersion:
    """One registered model: its directory plus the lineage metadata."""

    version: int
    path: str
    sha256: str
    parent_sha256: str | None
    created_unix: int
    data_fingerprint: str | None
    metrics: dict
    note: str | None

    @property
    def name(self) -> str:
        return f"v{self.version:07d}"


class ModelRegistry:
    """Filesystem model registry with an atomic "current" pointer.

    ``clock`` is injectable (seconds since epoch) so tests produce
    deterministic ``created_unix`` stamps.
    """

    def __init__(self, root: str, *, clock: Callable[[], float] | None = None):
        self.root = os.path.abspath(os.path.expanduser(root))
        # created_unix stamps are MEANT to be wall-clock (lineage
        # records correlate with logs outside the process); the clock
        # stays injectable, so tests are still deterministic
        # harlint: disable=HL004
        self._clock = clock or time.time
        os.makedirs(os.path.join(self.root, _VERSIONS), exist_ok=True)

    # ------------------------------------------------------------ ids

    def _next_id(self) -> int:
        """Allocate the next monotone version id.  Persisted in NEXT_ID
        (atomic tmp+rename) so a pruned id is never reissued; a missing
        counter file (pre-existing registries, manual surgery) falls
        back to max(existing)+1."""
        counter = os.path.join(self.root, _NEXT_ID)
        try:
            with open(counter) as f:
                nxt = int(f.read().strip())
        except (OSError, ValueError):
            existing = [v.version for v in self.versions()]
            nxt = max(existing, default=0) + 1
        _atomic_write(counter, str(nxt + 1))
        return nxt

    # ------------------------------------------------------- registry

    def register(
        self,
        save: Callable[[str], object] | None = None,
        *,
        metrics: dict | None = None,
        data_fingerprint: str | None = None,
        note: str | None = None,
        promote: bool = False,
    ) -> ModelVersion:
        """Allocate a version dir, let ``save(dir)`` write the artifact
        into it, fingerprint the result, and record lineage
        (parent_sha256 = the CURRENT incumbent's artifact hash).

        ``save=None`` registers a metadata-only version (an in-process
        model with no persistent form — e.g. the analytic demo model, or
        a smoke-test stub); it participates in lineage and promotion
        like any other.  ``promote=True`` promotes atomically after
        registering (first version of a fresh registry, typically).
        """
        version = self._next_id()
        cur = self.current()
        path = os.path.join(self.root, _VERSIONS, f"v{version:07d}")
        os.makedirs(path)
        try:
            if save is not None:
                save(path)
            meta = {
                "version": version,
                # metadata-only versions have no artifact bytes to hash;
                # a version-unique sentinel keeps the parent chain
                # non-degenerate (every empty dir hashes identically)
                "sha256": (
                    _dir_sha256(path)
                    if save is not None
                    else f"metadata-only:v{version:07d}"
                ),
                "parent_sha256": None if cur is None else cur.sha256,
                "created_unix": int(self._clock()),
                "data_fingerprint": data_fingerprint,
                "metrics": dict(metrics or {}),
                "note": note,
            }
            # the same fsync discipline as CURRENT/NEXT_ID (harlint
            # HL005): a bare buffered write could leave a promoted
            # version with a torn registry.json after power loss —
            # _load_version would return None and current() would
            # blind the whole lineage chain
            _atomic_write(
                os.path.join(path, _META), json.dumps(meta, indent=1)
            )
        except BaseException:
            shutil.rmtree(path, ignore_errors=True)  # no half-versions
            raise
        mv = self._load_version(path)
        if promote:
            self.promote(version)
        return mv

    def _load_version(self, path: str) -> ModelVersion | None:
        try:
            with open(os.path.join(path, _META)) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return None  # a half-deleted or foreign dir is not a version
        return ModelVersion(
            version=int(meta["version"]),
            path=path,
            sha256=meta["sha256"],
            parent_sha256=meta.get("parent_sha256"),
            created_unix=int(meta.get("created_unix", 0)),
            data_fingerprint=meta.get("data_fingerprint"),
            metrics=meta.get("metrics", {}),
            note=meta.get("note"),
        )

    def versions(self) -> list[ModelVersion]:
        """All registered versions, ascending."""
        vdir = os.path.join(self.root, _VERSIONS)
        out = []
        for name in sorted(os.listdir(vdir)):
            mv = self._load_version(os.path.join(vdir, name))
            if mv is not None:
                out.append(mv)
        return sorted(out, key=lambda v: v.version)

    def get(self, version: int) -> ModelVersion:
        path = os.path.join(self.root, _VERSIONS, f"v{int(version):07d}")
        mv = self._load_version(path)
        if mv is None:
            raise KeyError(f"no registered version {version}")
        return mv

    # ------------------------------------------------------- pointer

    def current(self) -> ModelVersion | None:
        """The promoted incumbent (None on a fresh registry)."""
        ptr = os.path.join(self.root, _CURRENT)
        if os.path.islink(ptr):
            target = os.readlink(ptr)
        elif os.path.isfile(ptr):
            with open(ptr) as f:
                target = f.read().strip()
        else:
            return None
        return self._load_version(
            os.path.join(self.root, os.path.normpath(target))
        )

    def promote(self, version: int, *, event: str = "promote") -> ModelVersion:
        """Atomically point CURRENT at ``version`` (symlink-or-rename:
        readers see the old pointer or the new one, never a torn state)
        and append the transition to the promotions log."""
        mv = self.get(version)
        prev = self.current()
        ptr = os.path.join(self.root, _CURRENT)
        target = os.path.join(_VERSIONS, mv.name)
        tmp = ptr + ".tmp"
        if os.path.lexists(tmp):
            os.remove(tmp)
        try:
            os.symlink(target, tmp)
        except OSError:
            _atomic_write(ptr, target)  # symlink-less filesystem
        else:
            os.replace(tmp, ptr)
            # a symlink has no data to fsync; the rename's durability
            # lives entirely in the directory entry
            _fsync_dir(self.root)
        _durable_append(
            os.path.join(self.root, _LOG),
            json.dumps(
                {
                    "event": event,
                    "version": mv.version,
                    "from_version": None if prev is None else prev.version,
                    "at_unix": int(self._clock()),
                }
            )
            + "\n",
        )
        return mv

    def rollback(self) -> ModelVersion:
        """Re-promote the version that was serving before the current
        one (from the promotions log), recording the transition as a
        ``rollback`` event.  Raises RuntimeError when there is no prior
        incumbent to fall back to."""
        cur = self.current()
        if cur is None:
            raise RuntimeError("nothing promoted; nothing to roll back")
        prev_version = None
        for line in self._log_lines():
            if line["version"] == cur.version and line["event"] != "rollback":
                prev_version = line["from_version"]
        if prev_version is None:
            raise RuntimeError(
                f"{cur.name} has no recorded predecessor to roll back to"
            )
        return self.promote(prev_version, event="rollback")

    def _log_lines(self) -> Iterable[dict]:
        try:
            with open(os.path.join(self.root, _LOG)) as f:
                return [json.loads(ln) for ln in f if ln.strip()]
        except OSError:
            return []

    def history(self) -> list[dict]:
        """The promote/rollback transitions, oldest first."""
        return list(self._log_lines())

    # --------------------------------------------------------- prune

    def prune(self, keep: int = 5) -> list[int]:
        """Delete the oldest versions beyond ``keep``, never the current
        incumbent or its recorded predecessor (the rollback target must
        survive a prune).  Returns the pruned version ids."""
        cur = self.current()
        protected = set()
        if cur is not None:
            protected.add(cur.version)
            for line in self._log_lines():
                if (
                    line["version"] == cur.version
                    and line["from_version"] is not None
                ):
                    protected.add(line["from_version"])
        versions = self.versions()
        pruned = []
        excess = len(versions) - max(int(keep), 0)
        for mv in versions:
            if excess <= 0:
                break
            if mv.version in protected:
                continue
            shutil.rmtree(mv.path, ignore_errors=True)
            pruned.append(mv.version)
            excess -= 1
        return pruned


# --------------------------------------------------------------------------
# Checkpoint-backed savers: register() plumbing for the two persistence
# families, threading the registry's lineage into the checkpoint meta so
# the artifact is self-describing even outside the registry dir.
# --------------------------------------------------------------------------


def register_neural(
    registry: ModelRegistry,
    model,
    model_name: str,
    *,
    metrics: dict | None = None,
    data_fingerprint: str | None = None,
    promote: bool = False,
    **save_kwargs,
) -> ModelVersion:
    """Register a trained NeuralClassifierModel as a full checkpoint
    (checkpoint.save_model) with lineage stamped into har_meta.json —
    the artifact is self-describing even copied out of the registry."""
    from har_tpu.checkpoint import save_model

    cur = registry.current()

    def save(path: str) -> None:
        save_model(
            path,
            model,
            model_name,
            # the allocated dir IS the version name (v%07d)
            version=int(os.path.basename(path)[1:]),
            parent_sha256=None if cur is None else cur.sha256,
            created_unix=int(registry._clock()),
            **save_kwargs,
        )

    return registry.register(
        save,
        metrics=metrics,
        data_fingerprint=data_fingerprint,
        note=f"neural:{model_name}",
        promote=promote,
    )


def register_classical(
    registry: ModelRegistry,
    model,
    *,
    metrics: dict | None = None,
    data_fingerprint: str | None = None,
    promote: bool = False,
    **save_kwargs,
) -> ModelVersion:
    """Register a classical model (checkpoint.save_classical_model)
    with the same lineage stamps."""
    from har_tpu.checkpoint import save_classical_model

    cur = registry.current()

    def save(path: str) -> None:
        save_classical_model(
            path,
            model,
            version=int(os.path.basename(path)[1:]),
            parent_sha256=None if cur is None else cur.sha256,
            created_unix=int(registry._clock()),
            **save_kwargs,
        )

    return registry.register(
        save,
        metrics=metrics,
        data_fingerprint=data_fingerprint,
        note=f"classical:{type(model).__name__}",
        promote=promote,
    )
