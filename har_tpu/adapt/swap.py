"""The closed adaptation loop: drift → retrain → shadow → hot-swap →
probation → (rollback | accept).

``AdaptationEngine`` is the controller that wires the pieces together
around a live ``FleetServer``:

  serving ──trigger fires──▶ retrain (caller's ``retrainer(job)``)
     ▲                            │ candidate registered (ModelRegistry)
     │                            ▼
     │◀──gates fail (incumbent  shadowing: candidate scores a mirrored
     │    keeps serving)          sample of live dispatches
     │                            │ gates pass
     │                            ▼
     │                        hot swap: registry.promote + FleetServer.
     │                          swap_model at a dispatch boundary —
     │                          zero windows dropped, in-flight batches
     │                          finish on the old model
     │                            │
     │◀──probation clean──────────┤
     │                            │ SLO / agreement regression
     │◀──auto-rollback: registry.rollback + swap back to the prior
             incumbent (stats.rollbacks counted)

Single-threaded like the engine it controls: ``step()`` is called from
the serving loop (the CLI's drive loop, a bench lane, or a transport
shim's timer) and never blocks serving beyond the synchronous
``retrainer`` call the caller chose to run there — a deployment that
wants retraining off-thread passes a retrainer that submits and returns
the handle's result on a later step (``RetrainPending``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

from har_tpu.adapt.registry import ModelRegistry
from har_tpu.adapt.shadow import ShadowConfig, ShadowEvaluator
from har_tpu.adapt.trigger import (
    ReplayBuffer,
    RetrainJob,
    RetrainTrigger,
    TriggerConfig,
)


class RetrainPending(Exception):
    """A retrainer may raise this to signal "job submitted, candidate
    not ready" — the engine stays in ``serving`` and re-runs the
    retrainer with the SAME job on later steps until it returns."""


@dataclasses.dataclass(frozen=True)
class AdaptationConfig:
    """Loop-level knobs (trigger/shadow carry their own configs)."""

    # dispatches a candidate may shadow before an undecided evaluation
    # is rejected (gates that cannot accumulate evidence must not pin
    # the loop in `shadowing` forever)
    max_shadow_dispatches: int = 64
    # post-swap watch: this many dispatches must complete without
    # regression before the swap is accepted
    probation_dispatches: int = 8
    # regression criteria inside probation: reverse-shadow agreement of
    # the OLD model vs the new incumbent below this floor ...
    probation_min_agreement: float = 0.95
    # ... with at least this much reverse evidence before agreement can
    # condemn the swap
    probation_min_windows: int = 16
    # ... or this fraction of probation dispatches breaching SLO
    probation_max_breach_frac: float = 0.5
    # ... or ANY dispatch failure during probation (the strictest
    # signal: the new model cannot score the live traffic at all)
    probation_fail_on_dispatch_failure: bool = True

    def __post_init__(self):
        if self.probation_dispatches < 1:
            raise ValueError("probation_dispatches must be >= 1")


class AdaptationEngine:
    """Drift-triggered retrain/shadow/swap/rollback controller.

    Parameters
    ----------
    server:
        The live ``FleetServer``.  The engine owns its dispatch tap.
    registry:
        Model lineage store.  A fresh registry gets the serving
        incumbent registered + promoted as the bootstrap version.
    retrainer:
        ``retrainer(job: RetrainJob) -> model`` — produces a candidate
        from the drifted-session replay (mixed into the caller's seed
        set; the engine does not prescribe how).  May raise
        ``RetrainPending`` to keep the job in flight across steps; any
        other exception rejects the job (counted, serving untouched).
    saver:
        Optional ``saver(model, path)`` used to persist candidates into
        their registry version dir (e.g. ``checkpoint.save_model``
        partial).  Without it candidates register metadata-only.
    clock:
        Injectable monotonic-seconds source shared with the trigger
        debounce — tests drive the whole loop with a FakeClock.
    """

    def __init__(
        self,
        server,
        registry: ModelRegistry,
        retrainer: Callable[[RetrainJob], object],
        *,
        config: AdaptationConfig | None = None,
        trigger: RetrainTrigger | None = None,
        trigger_config: TriggerConfig | None = None,
        shadow_config: ShadowConfig | None = None,
        saver: Callable | None = None,
        clock: Callable[[], float] | None = None,
    ):
        self.server = server
        self.registry = registry
        self.retrainer = retrainer
        self.config = config or AdaptationConfig()
        self.shadow_config = shadow_config or ShadowConfig()
        self._saver = saver
        self._clock = clock or time.monotonic
        self.trigger = trigger or RetrainTrigger(
            trigger_config, replay=ReplayBuffer(), clock=self._clock
        )
        self.state = "serving"
        self.log: list[dict] = []
        self.retrain_jobs = 0
        self.rejected_candidates = 0
        self.retrain_errors = 0
        self.registry_errors = 0
        # lineage bootstrap: the serving model becomes the promoted
        # incumbent so the first candidate has a parent and rollback
        # always has a target.  On a REUSED registry the convention is
        # that the caller serves the promoted incumbent's model — the
        # server's version label is synced to it either way, so
        # scored_by_version keys always map onto registry versions.
        cur = registry.current()
        if cur is None:
            cur = registry.register(
                None, note="incumbent:bootstrap", promote=True
            )
        server.model_version = cur.name
        self._pending_job: RetrainJob | None = None
        self._exclude: frozenset = frozenset()  # drifted sessions of
        #   the job under evaluation (agreement-gate exclusion set)
        self._shadow: ShadowEvaluator | None = None
        self._candidate = None  # (ModelVersion, model) under shadow
        self._shadow_start = 0  # stats.dispatches at shadow start
        self._probation = None  # baseline dict during probation
        server.set_dispatch_tap(self._tap)

    # ----------------------------------------------------------- tap

    def _tap(self, session_ids, windows, probs) -> bool:
        """The engine's single dispatch tap: replay capture always,
        shadow scoring (candidate or probation reverse-shadow) when one
        is active.  Return value = "shadow actually scored" (engine
        accounting)."""
        self.trigger.replay.add_batch(session_ids, windows)
        if self._shadow is not None:
            return self._shadow(session_ids, windows, probs)
        return False

    # ---------------------------------------------------------- step

    def step(self) -> dict:
        """Advance the loop one tick: pull drift state, run whichever
        transition is due, return ``status()``.  Safe to call at any
        cadence — every transition is edge-triggered and debounced."""
        self.trigger.observe_server(self.server)
        if self.state == "serving":
            self._step_serving()
        elif self.state == "shadowing":
            self._step_shadowing()
        elif self.state == "probation":
            self._step_probation()
        return self.status()

    def _note(self, event: str, **fields) -> None:
        self.log.append({"event": event, "at": self._clock(), **fields})

    def _step_serving(self) -> None:
        job = self._pending_job or self.trigger.poll()
        if job is None:
            return
        if self._pending_job is None:
            self.retrain_jobs += 1
            self._note(
                "trigger_fired",
                job_id=job.job_id,
                sessions=len(job.session_ids),
                channels=list(job.channels),
                reason=job.reason,
            )
        try:
            candidate = self.retrainer(job)
        except RetrainPending:
            self._pending_job = job  # re-poll the same job next step
            return
        except Exception as exc:
            self.retrain_errors += 1
            self._pending_job = None
            # re-arm the job's episodes: a persistent drift must be
            # able to fire again (after the cooldown) — one transient
            # retrain error must not disarm adaptation forever
            self.trigger.reopen(job)
            self._note(
                "retrain_failed",
                job_id=job.job_id,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            return
        self._pending_job = None
        save = (
            None
            if self._saver is None
            else (lambda path: self._saver(candidate, path))
        )
        from har_tpu.adapt.registry import data_fingerprint

        try:
            mv = self.registry.register(
                save,
                data_fingerprint=(
                    None
                    if job.replay is None
                    else data_fingerprint(job.replay)
                ),
                note=f"candidate:job{job.job_id}",
            )
        except Exception as exc:
            # registry I/O (disk full, permissions) must be contained
            # exactly like a retrainer failure: the candidate is
            # dropped, the incumbent keeps serving, the loop survives
            self.registry_errors += 1
            self.trigger.reopen(job)  # same re-arm as a retrain error
            self._note(
                "registry_failed",
                op="register",
                job_id=job.job_id,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            return
        # the drifted sessions are excluded from the agreement gate on
        # BOTH sides of the swap: pre-swap the incumbent is not a
        # trustworthy reference on them (a corrective candidate SHOULD
        # disagree there), post-swap the replaced model isn't either
        self._exclude = frozenset(job.session_ids)
        self._shadow = ShadowEvaluator(
            candidate,
            self.shadow_config,
            exclude_sessions=self._exclude,
            clock=self._clock,
        )
        self._candidate = (mv, candidate)
        # budget baseline counts dispatch ATTEMPT outcomes (successes
        # AND failures): a fleet whose every dispatch fails must still
        # run the evidence budget down and reject, not pin `shadowing`
        self._shadow_start = (
            self.server.stats.dispatches
            + self.server.stats.dispatch_failures
        )
        self.state = "shadowing"
        self._note("shadow_started", version=mv.name, job_id=job.job_id)

    def _step_shadowing(self) -> None:
        # live incumbent baseline for the optional latency gate: the
        # engine's own dispatch-stage mean (replaced each step — the
        # gate compares means, so only the latest baseline matters)
        disp = self.server.stats.dispatch
        if disp.count:
            self._shadow.set_incumbent_ms(disp.total_ms / disp.count)
        gates = self._shadow.gates()
        mv, candidate = self._candidate
        if gates["passed"]:
            self._swap_to(mv, candidate, gates)
            return
        waited = (
            self.server.stats.dispatches
            + self.server.stats.dispatch_failures
            - self._shadow_start
        )
        if waited >= self.config.max_shadow_dispatches:
            # undecided or failing after the evidence budget: the
            # incumbent keeps serving, the candidate stays in the
            # registry unpromoted (auditable, prunable)
            self.rejected_candidates += 1
            self._note(
                "candidate_rejected",
                version=mv.name,
                gates=gates,
                dispatches_waited=waited,
            )
            self._shadow = None
            self._candidate = None
            self.trigger.hold()
            self.state = "serving"

    def _swap_to(self, mv, candidate, gates: dict) -> None:
        stats = self.server.stats
        prev_version = self.server.model_version
        prev_model = self.server.model
        try:
            self.registry.promote(mv.version)
        except Exception as exc:
            # cannot record the promotion → do not swap: an unrecorded
            # incumbent would have no rollback trail.  The candidate is
            # rejected, the incumbent keeps serving.
            self.registry_errors += 1
            self.rejected_candidates += 1
            self._note(
                "registry_failed",
                op="promote",
                version=mv.name,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            self._shadow = None
            self._candidate = None
            self.trigger.hold()
            self.state = "serving"
            return
        self.server.swap_model(candidate, version=mv.name)
        self.server.reset_monitors()  # re-arm: fresh episodes only
        self.trigger.aggregator.reset()
        self.trigger.hold()
        # probation: reverse-shadow the REPLACED model against the new
        # incumbent's live traffic — disagreement now means the swap
        # changed fleet decisions more than the shadow sample promised
        self._shadow = ShadowEvaluator(
            prev_model,
            ShadowConfig(
                sample_every=1,
                min_windows=self.config.probation_min_windows,
            ),
            exclude_sessions=self._exclude,
            clock=self._clock,
        )
        self._candidate = None
        self._probation = {
            "version": mv.name,
            "prev_version": prev_version,
            "prev_model": prev_model,
            "dispatches0": stats.dispatches,
            "breaches0": stats.slo_breaches,
            "failures0": stats.dispatch_failures,
        }
        self.state = "probation"
        self._note(
            "swapped",
            version=mv.name,
            from_version=prev_version,
            shadow=gates,
        )

    def _step_probation(self) -> None:
        cfg = self.config
        stats = self.server.stats
        p = self._probation
        dispatches = stats.dispatches - p["dispatches0"]
        breaches = stats.slo_breaches - p["breaches0"]
        failures = stats.dispatch_failures - p["failures0"]
        regression = None
        if cfg.probation_fail_on_dispatch_failure and failures > 0:
            regression = f"{failures} dispatch failure(s) post-swap"
        elif (
            dispatches >= 2
            and breaches / dispatches > cfg.probation_max_breach_frac
        ):
            regression = (
                f"SLO regression: {breaches}/{dispatches} post-swap "
                "dispatches breached"
            )
        else:
            agr = self._shadow.agreement
            if (
                agr is not None
                and self._shadow.n_windows >= cfg.probation_min_windows
                and agr < cfg.probation_min_agreement
            ):
                regression = (
                    f"agreement regression: {agr:.4f} < "
                    f"{cfg.probation_min_agreement} vs prior incumbent"
                )
        if regression is not None:
            self._rollback(regression)
            return
        if dispatches >= cfg.probation_dispatches:
            self._note(
                "probation_passed",
                version=p["version"],
                dispatches=dispatches,
                reverse_agreement=self._shadow.agreement,
            )
            self._shadow = None
            self._probation = None
            self.state = "serving"

    def _rollback(self, reason: str) -> None:
        p = self._probation
        try:
            rolled = self.registry.rollback()
            registry_version = rolled.name
        except Exception as exc:
            # serving correctness over lineage: swap the prior model
            # back even when the registry write fails (the pointer can
            # be repaired; a regressing model serving the fleet cannot)
            self.registry_errors += 1
            registry_version = None
            self._note(
                "registry_failed",
                op="rollback",
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
        self.server.swap_model(p["prev_model"], version=p["prev_version"])
        self.server.stats.rollbacks += 1
        self.server.reset_monitors()
        self.trigger.aggregator.reset()
        self.trigger.hold()
        self._note(
            "rolled_back",
            to_version=p["prev_version"],
            registry_version=registry_version,
            from_version=p["version"],
            reason=reason,
        )
        self._shadow = None
        self._probation = None
        self.state = "serving"

    # -------------------------------------------------------- status

    def status(self) -> dict:
        """JSON-ready loop state for CLIs, bench lanes and the gate."""
        stats = self.server.stats
        out = {
            "state": self.state,
            "serving_version": self.server.model_version,
            "retrain_jobs": self.retrain_jobs,
            "retrain_errors": self.retrain_errors,
            "registry_errors": self.registry_errors,
            "rejected_candidates": self.rejected_candidates,
            "swaps": stats.model_swaps,
            "rollbacks": stats.rollbacks,
            "shadow_batches": stats.shadow_batches,
            "shadow_windows": stats.shadow_windows,
        }
        if self._shadow is not None:
            key = "shadow" if self.state == "shadowing" else "probation_shadow"
            out[key] = self._shadow.report()
        return out
