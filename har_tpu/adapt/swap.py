"""The closed adaptation loop: drift → retrain → shadow → hot-swap →
probation → (rollback | accept).

``AdaptationEngine`` is the controller that wires the pieces together
around a live ``FleetServer``:

  serving ──trigger fires──▶ retrain (caller's ``retrainer(job)``)
     ▲                            │ candidate registered (ModelRegistry)
     │                            ▼
     │◀──gates fail (incumbent  shadowing: candidate scores a mirrored
     │    keeps serving)          sample of live dispatches
     │                            │ gates pass
     │                            ▼
     │                        hot swap: registry.promote + FleetServer.
     │                          swap_model at a dispatch boundary —
     │                          zero windows dropped, in-flight batches
     │                          finish on the old model
     │                            │
     │◀──probation clean──────────┤
     │                            │ SLO / agreement regression
     │◀──auto-rollback: registry.rollback + swap back to the prior
             incumbent (stats.rollbacks counted)

Single-threaded like the engine it controls: ``step()`` is called from
the serving loop (the CLI's drive loop, a bench lane, or a transport
shim's timer) and never blocks serving beyond the synchronous
``retrainer`` call the caller chose to run there — a deployment that
wants retraining off-thread passes a retrainer that submits and returns
the handle's result on a later step (``RetrainPending``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

from har_tpu.adapt.registry import ModelRegistry
from har_tpu.adapt.shadow import ShadowConfig, ShadowEvaluator
from har_tpu.adapt.trigger import (
    ReplayBuffer,
    RetrainJob,
    RetrainTrigger,
    TriggerConfig,
)


class RetrainPending(Exception):
    """A retrainer may raise this to signal "job submitted, candidate
    not ready" — the engine stays in ``serving`` and re-runs the
    retrainer with the SAME job on later steps until it returns."""


@dataclasses.dataclass(frozen=True)
class AdaptationConfig:
    """Loop-level knobs (trigger/shadow carry their own configs)."""

    # dispatches a candidate may shadow before an undecided evaluation
    # is rejected (gates that cannot accumulate evidence must not pin
    # the loop in `shadowing` forever)
    max_shadow_dispatches: int = 64
    # post-swap watch: this many dispatches must complete without
    # regression before the swap is accepted
    probation_dispatches: int = 8
    # regression criteria inside probation: reverse-shadow agreement of
    # the OLD model vs the new incumbent below this floor ...
    probation_min_agreement: float = 0.95
    # ... with at least this much reverse evidence before agreement can
    # condemn the swap
    probation_min_windows: int = 16
    # ... or this fraction of probation dispatches breaching SLO
    probation_max_breach_frac: float = 0.5
    # ... or ANY dispatch failure during probation (the strictest
    # signal: the new model cannot score the live traffic at all)
    probation_fail_on_dispatch_failure: bool = True

    def __post_init__(self):
        if self.probation_dispatches < 1:
            raise ValueError("probation_dispatches must be >= 1")


class AdaptationEngine:
    """Drift-triggered retrain/shadow/swap/rollback controller.

    Parameters
    ----------
    server:
        The live ``FleetServer``.  The engine owns its dispatch tap.
    registry:
        Model lineage store.  A fresh registry gets the serving
        incumbent registered + promoted as the bootstrap version.
    retrainer:
        ``retrainer(job: RetrainJob) -> model`` — produces a candidate
        from the drifted-session replay (mixed into the caller's seed
        set; the engine does not prescribe how).  May raise
        ``RetrainPending`` to keep the job in flight across steps; any
        other exception rejects the job (counted, serving untouched).
    saver:
        Optional ``saver(model, path)`` used to persist candidates into
        their registry version dir (e.g. ``checkpoint.save_model``
        partial).  Without it candidates register metadata-only.
    clock:
        Injectable monotonic-seconds source shared with the trigger
        debounce — tests drive the whole loop with a FakeClock.
    """

    def __init__(
        self,
        server,
        registry: ModelRegistry,
        retrainer: Callable[[RetrainJob], object],
        *,
        config: AdaptationConfig | None = None,
        trigger: RetrainTrigger | None = None,
        trigger_config: TriggerConfig | None = None,
        shadow_config: ShadowConfig | None = None,
        saver: Callable | None = None,
        clock: Callable[[], float] | None = None,
        resume: bool = False,
        loader: Callable[[str], object] | None = None,
    ):
        self.server = server
        self.registry = registry
        self.retrainer = retrainer
        self.config = config or AdaptationConfig()
        self.shadow_config = shadow_config or ShadowConfig()
        self._saver = saver
        self._loader = loader
        self._clock = clock or time.monotonic
        self.trigger = trigger or RetrainTrigger(
            trigger_config, replay=ReplayBuffer(), clock=self._clock
        )
        self.state = "serving"
        self.log: list[dict] = []
        self.retrain_jobs = 0
        self.rejected_candidates = 0
        self.retrain_errors = 0
        self.registry_errors = 0
        self._pending_job: RetrainJob | None = None
        self._exclude: frozenset = frozenset()  # drifted sessions of
        #   the job under evaluation (agreement-gate exclusion set)
        self._shadow: ShadowEvaluator | None = None
        self._candidate = None  # (ModelVersion, model) under shadow
        self._shadow_start = 0  # stats.dispatches at shadow start
        self._probation = None  # baseline dict during probation
        if resume:
            # crash recovery (har_tpu.serve.recover): reconcile the
            # recovered fleet with the registry pointer and the
            # journaled episode state instead of bootstrapping
            self._resume()
        else:
            # lineage bootstrap: the serving model becomes the promoted
            # incumbent so the first candidate has a parent and
            # rollback always has a target.  On a REUSED registry the
            # convention is that the caller serves the promoted
            # incumbent's model — the server's version label is synced
            # to it either way, so scored_by_version keys always map
            # onto registry versions.
            cur = registry.current()
            if cur is None:
                cur = registry.register(
                    None, note="incumbent:bootstrap", promote=True
                )
            server.model_version = cur.name
        server.set_dispatch_tap(self._tap)
        # durability: the engine's episode/probation state rides the
        # fleet journal's snapshots, and every transition is journaled
        # as an `adapt` record — a half-finished promotion survives a
        # SIGKILL and resumes (or rolls back) on restore
        providers = getattr(server, "snapshot_providers", None)
        if providers is not None:
            providers["adapt"] = self._snapshot_state
            if getattr(server, "journal", None) is not None:
                # the server's attach-time snapshot predates this
                # registration: write one that carries the adapt extra,
                # so episode state recovers even when a crash lands
                # before the first cadence snapshot
                server.write_snapshot()

    # ----------------------------------------------------------- tap

    def _tap(self, session_ids, windows, probs) -> bool:
        """The engine's single dispatch tap: replay capture always,
        shadow scoring (candidate or probation reverse-shadow) when one
        is active.  Return value = "shadow actually scored" (engine
        accounting)."""
        self.trigger.replay.add_batch(session_ids, windows)
        if self._shadow is not None:
            return self._shadow(session_ids, windows, probs)
        return False

    # ---------------------------------------------------------- step

    def step(self) -> dict:
        """Advance the loop one tick: pull drift state, run whichever
        transition is due, return ``status()``.  Safe to call at any
        cadence — every transition is edge-triggered and debounced."""
        self.trigger.observe_server(self.server)
        if self.state == "serving":
            self._step_serving()
        elif self.state == "shadowing":
            self._step_shadowing()
        elif self.state == "probation":
            self._step_probation()
        return self.status()

    def _note(self, event: str, **fields) -> None:
        self.log.append({"event": event, "at": self._clock(), **fields})
        # every transition also lands in the fleet journal (t="adapt"),
        # so recovery can tell a promotion that concluded from one the
        # crash interrupted
        journal = getattr(self.server, "journal", None)
        if journal is not None:
            try:
                journal.append(
                    {"t": "adapt", "ev": event, "at": self._clock(),
                     **fields}
                )
            except TypeError:
                # a non-JSON-serializable field (shouldn't happen; all
                # note fields are scalars/lists) must not kill serving
                journal.append({"t": "adapt", "ev": event})

    def _chaos(self, point: str) -> None:
        journal = getattr(self.server, "journal", None)
        if journal is not None:
            journal.chaos_point(point)

    def _step_serving(self) -> None:
        job = self._pending_job or self.trigger.poll()
        if job is None:
            return
        if self._pending_job is None:
            self.retrain_jobs += 1
            self._note(
                "trigger_fired",
                job_id=job.job_id,
                sessions=len(job.session_ids),
                channels=list(job.channels),
                reason=job.reason,
            )
        try:
            candidate = self.retrainer(job)
        except RetrainPending:
            self._pending_job = job  # re-poll the same job next step
            return
        except Exception as exc:
            self.retrain_errors += 1
            self._pending_job = None
            # re-arm the job's episodes: a persistent drift must be
            # able to fire again (after the cooldown) — one transient
            # retrain error must not disarm adaptation forever
            self.trigger.reopen(job)
            self._note(
                "retrain_failed",
                job_id=job.job_id,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            return
        self._pending_job = None
        save = (
            None
            if self._saver is None
            else (lambda path: self._saver(candidate, path))
        )
        from har_tpu.adapt.registry import data_fingerprint

        try:
            mv = self.registry.register(
                save,
                data_fingerprint=(
                    None
                    if job.replay is None
                    else data_fingerprint(job.replay)
                ),
                note=f"candidate:job{job.job_id}",
            )
        except Exception as exc:
            # registry I/O (disk full, permissions) must be contained
            # exactly like a retrainer failure: the candidate is
            # dropped, the incumbent keeps serving, the loop survives
            self.registry_errors += 1
            self.trigger.reopen(job)  # same re-arm as a retrain error
            self._note(
                "registry_failed",
                op="register",
                job_id=job.job_id,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            return
        # the drifted sessions are excluded from the agreement gate on
        # BOTH sides of the swap: pre-swap the incumbent is not a
        # trustworthy reference on them (a corrective candidate SHOULD
        # disagree there), post-swap the replaced model isn't either
        self._start_shadow(
            mv, candidate, frozenset(job.session_ids),
            self.shadow_config,
        )
        self._note("shadow_started", version=mv.name, job_id=job.job_id)

    def _start_shadow(self, mv, candidate, exclude, shadow_config) -> None:
        """Enter ``shadowing`` for a registered candidate — shared by
        the drift-retrain path and operator-proposed candidates
        (``propose_candidate`` / the int8 promotion path)."""
        self._exclude = exclude
        self._shadow = ShadowEvaluator(
            candidate,
            shadow_config,
            exclude_sessions=exclude,
            clock=self._clock,
        )
        self._candidate = (mv, candidate)
        # budget baseline counts dispatch ATTEMPT outcomes (successes
        # AND failures): a fleet whose every dispatch fails must still
        # run the evidence budget down and reject, not pin `shadowing`
        self._shadow_start = (
            self.server.stats.dispatches
            + self.server.stats.dispatch_failures
        )
        self.state = "shadowing"

    # ------------------------------------------- proposed candidates

    def propose_candidate(
        self,
        candidate,
        *,
        note: str = "candidate:proposed",
        shadow_config: ShadowConfig | None = None,
    ) -> str:
        """Inject a candidate WITHOUT a drift trigger — same evidence
        discipline as a retrained one: register in the lineage, shadow
        against live traffic, gate, hot-swap at a dispatch boundary,
        probation with automatic rollback.  No session is excluded from
        the agreement gate (there is no drifted cohort: the incumbent
        is the trusted reference everywhere — exactly the stance a
        tier change wants).  Returns the registered version label;
        refuses while a shadow or probation is already in flight (one
        candidate at a time is the loop's whole safety story)."""
        if self.state != "serving":
            raise RuntimeError(
                f"cannot propose a candidate while {self.state!r}; "
                "wait for the loop to settle"
            )
        try:
            mv = self.registry.register(None, note=note)
        except Exception as exc:
            self.registry_errors += 1
            self._note(
                "registry_failed",
                op="register",
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            raise
        self._start_shadow(
            mv, candidate, frozenset(),
            shadow_config or self.shadow_config,
        )
        self._note("shadow_started", version=mv.name, proposed=note)
        return mv.name

    def propose_int8(
        self,
        *,
        max_latency_factor: float | None = 1.5,
        shadow_config: ShadowConfig | None = None,
    ) -> str:
        """THE quantization promotion path: quantize the serving
        incumbent to the int8 tier (har_tpu.quantize.quantize_serving —
        weights int8 on device, dequant traced into the jitted
        program), shadow the int8 scorer against the live f32 traffic,
        and gate on agreement PLUS a latency factor (an int8 tier that
        is slower than the f32 incumbent has no reason to exist) —
        then hot-swap at a dispatch boundary with probation and
        automatic rollback exactly like a retrain candidate.  Adoption
        is on measurement, not faith: a quantization that moves live
        decisions past the agreement floor is rejected with evidence
        in the registry, and a post-swap regression rolls back."""
        from har_tpu.quantize import quantize_serving

        candidate = quantize_serving(self.server.model)
        cfg = shadow_config or dataclasses.replace(
            self.shadow_config, max_latency_factor=max_latency_factor
        )
        return self.propose_candidate(
            candidate, note="candidate:int8", shadow_config=cfg
        )

    def _step_shadowing(self) -> None:
        # live incumbent baseline for the optional latency gate: the
        # engine's own dispatch-stage mean (replaced each step — the
        # gate compares means, so only the latest baseline matters)
        disp = self.server.stats.dispatch
        if disp.count:
            self._shadow.set_incumbent_ms(disp.total_ms / disp.count)
        gates = self._shadow.gates()
        mv, candidate = self._candidate
        if gates["passed"]:
            self._swap_to(mv, candidate, gates)
            return
        waited = (
            self.server.stats.dispatches
            + self.server.stats.dispatch_failures
            - self._shadow_start
        )
        if waited >= self.config.max_shadow_dispatches:
            # undecided or failing after the evidence budget: the
            # incumbent keeps serving, the candidate stays in the
            # registry unpromoted (auditable, prunable)
            self.rejected_candidates += 1
            self._note(
                "candidate_rejected",
                version=mv.name,
                gates=gates,
                dispatches_waited=waited,
            )
            self._shadow = None
            self._candidate = None
            self.trigger.hold()
            self.state = "serving"

    def _swap_to(self, mv, candidate, gates: dict) -> None:
        stats = self.server.stats
        prev_version = self.server.model_version
        prev_model = self.server.model
        try:
            self.registry.promote(mv.version)
        except Exception as exc:
            # cannot record the promotion → do not swap: an unrecorded
            # incumbent would have no rollback trail.  The candidate is
            # rejected, the incumbent keeps serving.
            self.registry_errors += 1
            self.rejected_candidates += 1
            self._note(
                "registry_failed",
                op="promote",
                version=mv.name,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            self._shadow = None
            self._candidate = None
            self.trigger.hold()
            self.state = "serving"
            return
        # the registry pointer is durable, the fleet swap is not yet: a
        # kill HERE is the half-finished promotion the recovery path
        # must complete (serve CURRENT) — the chaos harness pins it
        self._chaos("mid_promote")
        self.server.swap_model(candidate, version=mv.name)
        self.server.reset_monitors()  # re-arm: fresh episodes only
        self.trigger.aggregator.reset()
        self.trigger.hold()
        # probation: reverse-shadow the REPLACED model against the new
        # incumbent's live traffic — disagreement now means the swap
        # changed fleet decisions more than the shadow sample promised
        self._shadow = ShadowEvaluator(
            prev_model,
            ShadowConfig(
                sample_every=1,
                min_windows=self.config.probation_min_windows,
            ),
            exclude_sessions=self._exclude,
            clock=self._clock,
        )
        self._candidate = None
        self._probation = {
            "version": mv.name,
            "prev_version": prev_version,
            "prev_model": prev_model,
            "dispatches0": stats.dispatches,
            "breaches0": stats.slo_breaches,
            "failures0": stats.dispatch_failures,
        }
        self.state = "probation"
        self._note(
            "swapped",
            version=mv.name,
            from_version=prev_version,
            shadow=gates,
        )
        # the 'swapped' record must be durable WITH the swap record: a
        # kill after the swap flushed but before this note would
        # otherwise recover into plain serving and skip probation —
        # the promoted candidate would run with no watchdog
        journal = getattr(self.server, "journal", None)
        if journal is not None:
            journal.flush()

    def _step_probation(self) -> None:
        cfg = self.config
        stats = self.server.stats
        p = self._probation
        dispatches = stats.dispatches - p["dispatches0"]
        breaches = stats.slo_breaches - p["breaches0"]
        failures = stats.dispatch_failures - p["failures0"]
        regression = None
        if cfg.probation_fail_on_dispatch_failure and failures > 0:
            regression = f"{failures} dispatch failure(s) post-swap"
        elif (
            dispatches >= 2
            and breaches / dispatches > cfg.probation_max_breach_frac
        ):
            regression = (
                f"SLO regression: {breaches}/{dispatches} post-swap "
                "dispatches breached"
            )
        else:
            agr = self._shadow.agreement
            if (
                agr is not None
                and self._shadow.n_windows >= cfg.probation_min_windows
                and agr < cfg.probation_min_agreement
            ):
                regression = (
                    f"agreement regression: {agr:.4f} < "
                    f"{cfg.probation_min_agreement} vs prior incumbent"
                )
        if regression is not None:
            self._rollback(regression)
            return
        if dispatches >= cfg.probation_dispatches:
            self._note(
                "probation_passed",
                version=p["version"],
                dispatches=dispatches,
                reverse_agreement=self._shadow.agreement,
            )
            self._shadow = None
            self._probation = None
            self.state = "serving"

    def _rollback(self, reason: str) -> None:
        p = self._probation
        try:
            rolled = self.registry.rollback()
            registry_version = rolled.name
        except Exception as exc:
            # serving correctness over lineage: swap the prior model
            # back even when the registry write fails (the pointer can
            # be repaired; a regressing model serving the fleet cannot)
            self.registry_errors += 1
            registry_version = None
            self._note(
                "registry_failed",
                op="rollback",
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
        # the rollback DECISION (the registry event, or the journaled
        # registry_failed record when the pointer write failed) must be
        # durable before the swap-back: a kill in between must leave
        # recovery knowing a rollback was owed, not guessing
        journal = getattr(self.server, "journal", None)
        if journal is not None:
            journal.flush()
        # mirror of mid_promote: pointer rolled back, swap-back not yet
        # applied — recovery must land the fleet on CURRENT
        self._chaos("mid_rollback")
        self.server.swap_model(p["prev_model"], version=p["prev_version"])
        self.server.stats.rollbacks += 1
        self.server.reset_monitors()
        self.trigger.aggregator.reset()
        self.trigger.hold()
        self._note(
            "rolled_back",
            to_version=p["prev_version"],
            registry_version=registry_version,
            from_version=p["version"],
            reason=reason,
        )
        # durable WITH the swap-back it concludes (mirror of _swap_to's
        # flush): a kill between them would otherwise re-enter a
        # phantom probation for the already-rolled-back version
        journal = getattr(self.server, "journal", None)
        if journal is not None:
            journal.flush()
        self._shadow = None
        self._probation = None
        self.state = "serving"

    # ---------------------------------------------------- durability

    def _snapshot_state(self) -> dict:
        """Episode + loop state persisted inside the fleet journal's
        snapshots (FleetServer.snapshot_providers).  A candidate under
        shadow is deliberately NOT persisted — a model object has no
        journal form; recovery abandons an in-flight shadow evaluation
        (the candidate stays registered unpromoted, the trigger
        re-fires for a persistent drift)."""
        agg = {}
        for sid, st in self.trigger.aggregator._sessions.items():
            agg[str(sid)] = {
                "onset": st.onset,
                "channels": sorted(st.channels),
                "last_seen": st.last_seen,
                "clean_streak": st.clean_streak,
                "alerted_onset": st.alerted_onset,
                "last_n": st.last_n,
                "last_gen": st.last_gen,
            }
        return {
            "state": self.state,
            "probation": (
                None
                if self._probation is None
                else {
                    "version": self._probation["version"],
                    "prev_version": self._probation["prev_version"],
                }
            ),
            "trigger": {
                "last_fired": self.trigger._last_fired,
                "n_jobs": self.trigger._n_jobs,
            },
            "counters": {
                "retrain_jobs": self.retrain_jobs,
                "rejected_candidates": self.rejected_candidates,
                "retrain_errors": self.retrain_errors,
                "registry_errors": self.registry_errors,
            },
            "aggregator": agg,
        }

    def _resume(self) -> None:
        """Crash-recovery reconciliation (``resume=True``): restore the
        journaled loop state and resolve any half-finished transition.

        The registry pointer is the durable source of truth for WHICH
        version should serve: a kill between ``registry.promote`` and
        the fleet swap (or between ``registry.rollback`` and the
        swap-back) leaves the pointer ahead of the fleet — recovery
        completes the swap to CURRENT via ``loader`` and, for a
        promotion, resumes probation from a fresh baseline.  An
        in-flight shadow evaluation is abandoned cleanly (candidate
        stays registered unpromoted; a persistent drift re-fires after
        the cooldown)."""
        from har_tpu.adapt.trigger import _SessionDrift

        server = self.server
        snap = (getattr(server, "recovered_extra", None) or {}).get(
            "adapt"
        ) or {}
        for k, v in (snap.get("counters") or {}).items():
            if hasattr(self, k):
                setattr(self, k, int(v))
        trig = snap.get("trigger") or {}
        if "last_fired" in trig:
            self.trigger._last_fired = float(trig["last_fired"])
        if "n_jobs" in trig:
            self.trigger._n_jobs = int(trig["n_jobs"])
        # episode state: restored per session so recovery does not
        # double-count drift evidence or forget an alerted episode
        sid_map = {str(sid): sid for sid in server.sessions}
        for key, st in (snap.get("aggregator") or {}).items():
            sid = sid_map.get(key)
            if sid is None:
                continue
            s = _SessionDrift()
            s.onset = st.get("onset")
            s.channels = set(st.get("channels") or [])
            s.last_seen = float(st.get("last_seen", -float("inf")))
            s.clean_streak = int(st.get("clean_streak", 0))
            s.alerted_onset = st.get("alerted_onset")
            s.last_n = int(st.get("last_n", -1))
            s.last_gen = st.get("last_gen")
            self.trigger.aggregator._sessions[sid] = s
        # loop state at the crash: journal suffix overrides snapshot
        state = snap.get("state", "serving")
        probation = snap.get("probation")
        pending_rollback = None  # regression decided, swap-back unproven
        for rec in getattr(server, "recovered_adapt_records", []):
            ev = rec.get("ev")
            if ev == "shadow_started":
                state = "shadowing"
            elif ev in ("swapped", "recovery_completed_promotion"):
                state = "probation"
                probation = {
                    "version": rec.get("version"),
                    "prev_version": rec.get("from_version"),
                }
                pending_rollback = None
            elif ev == "recovery_resumed_probation":
                # a PRIOR recovery resumed probation: a second crash
                # must resume it again, not forget it
                state = "probation"
                if probation is None:
                    probation = {
                        "version": rec.get("version"),
                        "prev_version": None,
                    }
            elif ev in (
                "recovery_completed_rollback",
                "recovery_abandoned_shadow",
                "recovery_probation_unresumable",
                "recovery_probation_superseded",
                "recovery_rollback_unresumable",
            ):
                state = "serving"
                probation = None
                pending_rollback = None
            elif ev == "registry_failed" and rec.get("op") == "rollback":
                # the live path swaps back even when the pointer write
                # fails ("serving correctness over lineage"); a kill
                # between this record and the swap-back must not leave
                # the regressing model serving — remember the intent
                pending_rollback = probation
                state = "serving"
                probation = None
            elif ev in (
                "rolled_back", "probation_passed", "candidate_rejected",
                "retrain_failed", "registry_failed",
            ):
                if ev == "rolled_back":
                    # the swap-back is noted AFTER it applies: proven
                    pending_rollback = None
                state = "serving"
                probation = None
        # a regression verdict whose rollback never finished (registry
        # write failed, then the kill hit before the swap-back): finish
        # it now, exactly as the live path would have
        completed_pending_rollback = False
        if (
            pending_rollback is not None
            and server.model_version == pending_rollback.get("version")
        ):
            prev_version = pending_rollback.get("prev_version")
            prev_model = None
            if self._loader is not None:
                try:
                    prev_model = self._loader(prev_version)
                except Exception:
                    prev_model = None
            if prev_model is None:
                # cannot load the prior incumbent: the condemned model
                # keeps serving, but NEVER silently — the operator (and
                # the journal) get the unresumable verdict
                self._note(
                    "recovery_rollback_unresumable",
                    version=pending_rollback.get("version"),
                    prev_version=prev_version,
                )
            else:
                try:
                    self.registry.rollback()  # retry the pointer write
                except Exception:
                    self.registry_errors += 1
                server.swap_model(prev_model, version=prev_version)
                server.stats.rollbacks += 1
                server.reset_monitors()
                self.trigger.aggregator.reset()
                self.trigger.hold()
                completed_pending_rollback = True
                self._note(
                    "recovery_completed_rollback",
                    version=prev_version,
                    from_version=pending_rollback.get("version"),
                )
        # registry reconciliation: the pointer moved but the fleet
        # didn't — complete the half-finished transition.  Skipped
        # after a completed pending rollback whose pointer retry failed
        # again: the pointer then still names the REGRESSING version,
        # and "serving correctness over lineage" wins.
        cur = self.registry.current()
        if completed_pending_rollback:
            cur = None
        completed_promote = False
        if cur is not None and cur.name != server.model_version:
            if self._loader is None:
                raise RuntimeError(
                    "recovery found registry CURRENT "
                    f"({cur.name}) != serving version "
                    f"({server.model_version}) but no loader was given; "
                    "pass loader=version_label->model to resume"
                )
            prev_version = server.model_version
            prev_model = server.model
            last_event = None
            for line in self.registry.history():
                last_event = line.get("event")
            server.swap_model(self._loader(cur.name), version=cur.name)
            server.reset_monitors()
            self.trigger.aggregator.reset()
            self.trigger.hold()
            if last_event == "promote":
                # finish the promotion: watch the completed swap
                completed_promote = True
                state = "probation"
                probation = {
                    "version": cur.name, "prev_version": prev_version,
                }
                self._probation_models = (prev_version, prev_model)
                self._note(
                    "recovery_completed_promotion",
                    version=cur.name,
                    from_version=prev_version,
                )
            else:  # rollback concluded: serve the restored incumbent
                state = "serving"
                probation = None
                self._note(
                    "recovery_completed_rollback",
                    version=cur.name,
                    from_version=prev_version,
                )
        if state == "shadowing":
            # the candidate model died with the process: abandon the
            # evaluation; the registry still holds the artifact
            self.trigger.hold()
            self._note("recovery_abandoned_shadow")
            state = "serving"
        if (
            state == "probation"
            and probation is not None
            and probation.get("version") != server.model_version
        ):
            # the journal proves a later swap superseded the probation
            # target (e.g. the swap-back applied but its 'rolled_back'
            # note died in the buffer): nothing left to watch
            self._note(
                "recovery_probation_superseded",
                version=probation.get("version"),
                serving=server.model_version,
            )
            state = "serving"
            probation = None
        if state == "probation" and probation is not None:
            prev_version = probation.get("prev_version")
            prev_model = None
            if completed_promote:
                prev_model = self._probation_models[1]
            elif self._loader is not None and prev_version:
                try:
                    prev_model = self._loader(prev_version)
                except Exception:
                    prev_model = None
            if prev_model is None:
                # cannot reverse-shadow or roll back without the prior
                # model: keep serving the incumbent, say so loudly
                self._note(
                    "recovery_probation_unresumable",
                    version=probation.get("version"),
                )
                state = "serving"
            else:
                stats = server.stats
                self._shadow = ShadowEvaluator(
                    prev_model,
                    ShadowConfig(
                        sample_every=1,
                        min_windows=self.config.probation_min_windows,
                    ),
                    clock=self._clock,
                )
                self._probation = {
                    "version": probation.get("version"),
                    "prev_version": prev_version,
                    "prev_model": prev_model,
                    "dispatches0": stats.dispatches,
                    "breaches0": stats.slo_breaches,
                    "failures0": stats.dispatch_failures,
                }
                self._note(
                    "recovery_resumed_probation",
                    version=probation.get("version"),
                )
        self.state = state

    # -------------------------------------------------------- status

    def status(self) -> dict:
        """JSON-ready loop state for CLIs, bench lanes and the gate."""
        stats = self.server.stats
        out = {
            "state": self.state,
            "serving_version": self.server.model_version,
            "retrain_jobs": self.retrain_jobs,
            "retrain_errors": self.retrain_errors,
            "registry_errors": self.registry_errors,
            "rejected_candidates": self.rejected_candidates,
            "swaps": stats.model_swaps,
            "rollbacks": stats.rollbacks,
            "shadow_batches": stats.shadow_batches,
            "shadow_windows": stats.shadow_windows,
        }
        if self._shadow is not None:
            key = "shadow" if self.state == "shadowing" else "probation_shadow"
            out[key] = self._shadow.report()
        return out
