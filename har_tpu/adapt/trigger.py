"""Fleet-level drift aggregation → debounced retrain trigger.

Per-session ``DriftMonitor``s (har_tpu.monitoring) answer "is THIS
stream out of distribution" — the wrong altitude for a retrain decision:
one wearer re-mounting a sensor is personalization, not population
drift; K wearers drifting on the SAME channels inside one window is the
signal SparkNet-style periodic refresh should consume.  This module is
that escalation layer:

  ``DriftAggregator`` — consumes per-session ``DriftReport``s (usually
    straight from ``FleetServer.drift_report``), tracks which sessions
    are in an active drift episode and which channels each episode
    implicates.  De-duplication is by ``DriftReport.onset``: one episode
    alerts once, and a monitor ``reset()`` after a model swap re-arms
    cleanly (the new episode gets a new onset).  Hysteresis on recovery:
    a session leaves the drifted set only after ``recovery_patience``
    consecutive clean reports — a flapping monitor cannot strobe the
    trigger.

  ``RetrainTrigger`` — fires a ``RetrainJob`` when >= ``min_sessions``
    sessions share a drifted channel within ``window_s``, then holds a
    ``cooldown_s`` debounce (a retrain in flight must not be re-enqueued
    by the same population event).  The job carries the drifted session
    ids, the implicated channels, and a bounded ``ReplayBuffer`` sample
    of those sessions' recent windows — what the retrainer mixes into
    the seed training set.

Host-side and allocation-light like the rest of the serving stack; the
clock is injectable so every debounce is testable with a FakeClock.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Hashable, Sequence

import numpy as np


class ReplayBuffer:
    """Bounded per-session store of recent raw windows.

    The adaptation engine feeds it from the dispatch tap (every window
    the fleet actually scored is a candidate), and a fired RetrainJob
    samples the DRIFTED sessions' entries — the distribution the
    incumbent is failing on, in the proportion it is arriving.
    """

    def __init__(self, per_session: int = 32):
        if per_session <= 0:
            raise ValueError("per_session must be positive")
        self.per_session = int(per_session)
        self._buf: dict[Hashable, deque] = {}

    def add(self, session_id: Hashable, window: np.ndarray) -> None:
        buf = self._buf.get(session_id)
        if buf is None:
            buf = self._buf[session_id] = deque(maxlen=self.per_session)
        # COPY, never a view: the dispatch tap hands this buffer views
        # of the engine's pooled staging slabs (the fused hot loop
        # recycles a slab as soon as its ticket retires) — storing the
        # view would let a later dispatch overwrite retained replay data
        buf.append(np.array(window, np.float32, copy=True))

    def add_batch(
        self, session_ids: Sequence[Hashable], windows: np.ndarray
    ) -> None:
        for sid, win in zip(session_ids, windows):
            self.add(sid, win)

    def sample(
        self,
        session_ids: Sequence[Hashable] | None = None,
        max_windows: int = 512,
    ) -> np.ndarray | None:
        """Windows from the named sessions (all sessions when None),
        capped at ``max_windows``; None when empty.  The cap is taken
        ROUND-ROBIN across sessions, newest first within each — a drift
        event spanning more sessions than the cap covers still samples
        every session instead of exhausting the budget on the first
        few."""
        sids = list(self._buf) if session_ids is None else list(session_ids)
        queues = [
            list(reversed(self._buf[sid]))
            for sid in sids
            if self._buf.get(sid)
        ]
        out: list[np.ndarray] = []
        max_windows = int(max_windows)
        while queues and len(out) < max_windows:
            still = []
            for q in queues:
                out.append(q.pop(0))
                if len(out) >= max_windows:
                    break
                if q:
                    still.append(q)
            queues = still
        if not out:
            return None
        return np.stack(out)

    def __len__(self) -> int:
        return sum(len(b) for b in self._buf.values())


@dataclasses.dataclass(frozen=True)
class RetrainJob:
    """One fired trigger: everything a retrainer needs to act."""

    job_id: int
    created_at: float  # trigger clock seconds
    session_ids: tuple  # the drifted sessions behind the escalation
    channels: tuple[int, ...]  # shared drifted channel indices
    replay: np.ndarray | None  # (n, T, C) drifted-session windows
    reason: str


@dataclasses.dataclass(frozen=True)
class TriggerConfig:
    """Escalation thresholds and debounce for the retrain trigger."""

    # fleet escalation: this many sessions drifting on a COMMON channel
    min_sessions: int = 3
    # ... with their latest drift evidence inside this window
    window_s: float = 120.0
    # refractory period after a fired job: the same population event
    # must not enqueue a second retrain while the first is in flight
    cooldown_s: float = 600.0
    # consecutive clean reports before a session leaves the drifted set
    # (hysteresis — the exit threshold is stickier than the entry one,
    # which DriftMonitor's own patience already debounces)
    recovery_patience: int = 3
    # per-channel thresholds for "this channel is implicated", matching
    # DriftMonitor's defaults so a drifting verdict always implicates
    # at least one channel
    z_threshold: float = 3.0
    scale_threshold: float = 0.69
    # replay windows handed to the retrainer per job
    max_replay_windows: int = 512

    def __post_init__(self):
        if self.min_sessions <= 0:
            raise ValueError("min_sessions must be positive")
        if self.recovery_patience < 1:
            raise ValueError("recovery_patience must be >= 1")


class _SessionDrift:
    """Aggregator-side view of one session's drift episode."""

    __slots__ = ("onset", "channels", "last_seen", "clean_streak",
                 "alerted_onset", "last_n", "last_gen")

    def __init__(self):
        self.onset = None
        self.channels: set[int] = set()
        self.last_seen = -float("inf")
        self.clean_streak = 0
        self.alerted_onset = None  # episode already folded into a job
        self.last_n = -1  # n_samples watermark within one generation:
        #   equality means the same stored report re-observed (stale)
        self.last_gen = None  # DriftReport.generation watermark: a
        #   change means the monitor was reset — onset indices restart
        #   with it, so the aggregator must not equate a post-reset
        #   onset with a numerically equal pre-reset one


class DriftAggregator:
    """Per-session episode tracking with onset de-duplication."""

    def __init__(
        self,
        config: TriggerConfig | None = None,
        *,
        clock: Callable[[], float] | None = None,
    ):
        self.config = config or TriggerConfig()
        self._clock = clock or time.monotonic
        self._sessions: dict[Hashable, _SessionDrift] = {}

    def observe(self, session_id: Hashable, report) -> None:
        """Absorb one session's latest DriftReport (None is a no-op)."""
        if report is None:
            return
        cfg = self.config
        st = self._sessions.get(session_id)
        if st is None:
            st = self._sessions[session_id] = _SessionDrift()
        now = self._clock()
        gen = getattr(report, "generation", 0)
        if st.last_gen is not None and gen != st.last_gen:
            # monitor reset between observations (generation bumped):
            # episode bookkeeping restarts with it — onset indices are
            # relative to the reset, even when the new n_samples lands
            # exactly on the old watermark
            st.onset = None
            st.channels = set()
            st.alerted_onset = None
            st.clean_streak = 0
        elif report.n_samples < st.last_n:
            # same fallback for generation-less reports (hand-built
            # DriftReports, foreign monitors): n restarting = a reset
            st.onset = None
            st.channels = set()
            st.alerted_onset = None
            st.clean_streak = 0
        elif report.n_samples == st.last_n and st.last_n >= 0:
            # the SAME stored report re-observed (the engine's step()
            # can run at any cadence over FleetServer.drift_report): no
            # new evidence — re-counting it would defeat the recovery
            # hysteresis and keep last_seen fresh on a dead stream
            return
        st.last_gen = gen
        st.last_n = report.n_samples
        if report.drifting:
            if report.onset != st.onset:
                if st.onset is None:
                    # a genuinely NEW episode (the previous one ended
                    # through the hysteresis below, or via a detected
                    # monitor reset): previous alert bookkeeping is void
                    st.alerted_onset = None
                elif st.alerted_onset == st.onset:
                    # the monitor flapped (one clean chunk cleared ITS
                    # onset) but the hysteresis says this is the SAME
                    # ongoing episode — carry the alerted mark onto the
                    # new onset so it cannot re-alert
                    st.alerted_onset = report.onset
                # channels re-derive from CURRENT evidence on any onset
                # change — an episode must not inherit the implicated
                # channels of the one it replaced
                st.channels = set()
                st.onset = report.onset
            st.clean_streak = 0
            st.last_seen = now
            z = np.asarray(report.location_z)
            r = np.abs(np.asarray(report.scale_log_ratio))
            st.channels.update(
                int(c)
                for c in np.flatnonzero(
                    (z > cfg.z_threshold) | (r > cfg.scale_threshold)
                )
            )
            if not st.channels:
                # drifting verdict but nothing currently over the
                # aggregator's thresholds (EWMA mid-decay): keep the
                # episode alive on its historically worst channel
                st.channels.add(int(report.worst_channel))
        else:
            st.clean_streak += 1
            if st.clean_streak >= cfg.recovery_patience:
                # hysteresis satisfied: the episode is over
                st.onset = None
                st.channels = set()
                st.alerted_onset = None

    def drifted(self, now: float | None = None) -> dict:
        """{session_id: channels} for sessions in an active, recent,
        not-yet-alerted episode."""
        now = self._clock() if now is None else now
        cfg = self.config
        return {
            sid: set(st.channels)
            for sid, st in self._sessions.items()
            if st.onset is not None
            and st.alerted_onset != st.onset
            and (now - st.last_seen) <= cfg.window_s
        }

    def mark_alerted(self, session_ids) -> None:
        """These sessions' CURRENT episodes were folded into a job —
        they must not count toward the next escalation until they
        recover and re-drift (a new onset)."""
        for sid in session_ids:
            st = self._sessions.get(sid)
            if st is not None:
                st.alerted_onset = st.onset

    def unmark_alerted(self, session_ids) -> None:
        """Undo mark_alerted for still-active episodes — the job their
        alert fed FAILED (retrain error), so a persistent episode must
        be allowed to fire again once the cooldown passes."""
        for sid in session_ids:
            st = self._sessions.get(sid)
            if st is not None and st.onset is not None:
                st.alerted_onset = None

    def reset(self) -> None:
        """Drop all episode state (the adaptation engine calls this
        alongside FleetServer.reset_monitors after a swap/rollback:
        every monitor restarted, so every tracked episode is void)."""
        self._sessions.clear()


class RetrainTrigger:
    """DriftAggregator + escalation rule + cooldown → RetrainJob queue."""

    def __init__(
        self,
        config: TriggerConfig | None = None,
        *,
        replay: ReplayBuffer | None = None,
        clock: Callable[[], float] | None = None,
    ):
        self.config = config or TriggerConfig()
        self.replay = replay if replay is not None else ReplayBuffer()
        self._clock = clock or time.monotonic
        self.aggregator = DriftAggregator(self.config, clock=self._clock)
        self._last_fired = -float("inf")
        self._n_jobs = 0

    def observe(self, session_id: Hashable, report) -> None:
        self.aggregator.observe(session_id, report)

    def observe_server(self, server) -> None:
        """Pull every session's latest drift report from a FleetServer
        (sessions without monitors report None and are skipped)."""
        for sid in server.sessions:
            self.observe(sid, server.drift_report(sid))

    def observe_workers(self, servers) -> None:
        """Fleet-GLOBAL escalation across worker partitions (the
        cluster control plane, har_tpu.serve.cluster): pull every
        partition's latest reports into the ONE aggregator, so K
        sessions drifting on a common channel fire the trigger no
        matter how the router spread them across workers — the same
        population event that would be invisible to K per-worker
        triggers each seeing fewer than ``min_sessions`` of it.
        Session ids must be cluster-unique (the router guarantees it:
        a session lives on exactly one worker)."""
        for server in servers:
            self.observe_server(server)

    def hold(self) -> None:
        """Restart the cooldown without firing — called after a swap or
        rollback so the population event that just resolved cannot
        immediately enqueue another retrain."""
        self._last_fired = self._clock()

    def reopen(self, job: RetrainJob) -> None:
        """A fired job failed before producing a candidate: re-arm its
        sessions' episodes so a PERSISTENT drift fires again after the
        cooldown (the cooldown itself stays — a failing retrainer must
        not be hammered)."""
        self.aggregator.unmark_alerted(job.session_ids)

    def poll(self) -> RetrainJob | None:
        """Fire a RetrainJob when the escalation rule holds and the
        cooldown has passed; None otherwise."""
        cfg = self.config
        now = self._clock()
        if (now - self._last_fired) < cfg.cooldown_s:
            return None
        drifted = self.aggregator.drifted(now)
        if len(drifted) < cfg.min_sessions:
            return None
        # the COMMON-channel rule: population drift means the same
        # physical channel moved for many wearers (a gain change, a
        # firmware update), not K unrelated personal anomalies
        counts: dict[int, int] = {}
        for channels in drifted.values():
            for c in channels:
                counts[c] = counts.get(c, 0) + 1
        shared = sorted(c for c, n in counts.items() if n >= cfg.min_sessions)
        if not shared:
            return None
        sessions = tuple(
            sid
            for sid, channels in drifted.items()
            if channels & set(shared)
        )
        self.aggregator.mark_alerted(sessions)
        self._last_fired = now
        self._n_jobs += 1
        return RetrainJob(
            job_id=self._n_jobs,
            created_at=now,
            session_ids=sessions,
            channels=tuple(shared),
            replay=self.replay.sample(
                sessions, max_windows=cfg.max_replay_windows
            ),
            reason=(
                f"{len(sessions)} sessions drifted on channel(s) "
                f"{list(shared)} within {cfg.window_s:.0f}s"
            ),
        )
