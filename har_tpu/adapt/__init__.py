"""Online adaptation: the closed loop that keeps a deployed fleet's
model matched to its drifting input distribution.

The paper's deployment scenario — continuous monitoring of elderly
wearers — is exactly where a frozen classifier decays (remounted
sensors, gait changes, new users).  ``har_tpu.monitoring`` detects the
decay per session and ``har_tpu.serve`` serves one compiled model
forever; this package closes the loop between them:

  registry.py   versioned model lineage (monotone ids, parent hashes,
                data fingerprints, atomic current pointer,
                promote/rollback/prune)
  trigger.py    fleet-level drift aggregation → debounced RetrainJob
                (K sessions, common channels, onset-deduplicated,
                hysteresis on recovery) + bounded replay buffer
  shadow.py     candidate scoring on mirrored live dispatches
                (bounded fraction, off the serving critical path) with
                promotion gates
  swap.py       the AdaptationEngine controller: retrain → shadow →
                zero-drop hot-swap at a dispatch boundary → probation
                with automatic rollback
  smoke.py      the release gate's end-to-end loop check

See docs/adaptation.md for the architecture and the test-pinned
contracts (zero-drop swap, gate-failure containment, auto-rollback).
"""

from har_tpu.adapt.registry import (
    ModelRegistry,
    ModelVersion,
    data_fingerprint,
    register_classical,
    register_neural,
)
from har_tpu.adapt.shadow import ShadowConfig, ShadowEvaluator
from har_tpu.adapt.smoke import adapt_smoke
from har_tpu.adapt.swap import (
    AdaptationConfig,
    AdaptationEngine,
    RetrainPending,
)
from har_tpu.adapt.trigger import (
    DriftAggregator,
    ReplayBuffer,
    RetrainJob,
    RetrainTrigger,
    TriggerConfig,
)

__all__ = [
    "AdaptationConfig",
    "AdaptationEngine",
    "DriftAggregator",
    "ModelRegistry",
    "ModelVersion",
    "ReplayBuffer",
    "RetrainJob",
    "RetrainPending",
    "RetrainTrigger",
    "ShadowConfig",
    "ShadowEvaluator",
    "TriggerConfig",
    "adapt_smoke",
    "data_fingerprint",
    "register_classical",
    "register_neural",
]
