"""Hyperparameter tuning: k-fold cross-validation + parameter grids."""

from har_tpu.tuning.cross_validator import (
    CrossValidator,
    CrossValidatorModel,
    kfold_indices,
    param_grid,
)
from har_tpu.tuning.mllib_cv import (
    REFERENCE_GRID,
    MLlibCVResult,
    mllib_cross_validate,
)

__all__ = [
    "REFERENCE_GRID",
    "MLlibCVResult",
    "mllib_cross_validate",
    "CrossValidator",
    "CrossValidatorModel",
    "kfold_indices",
    "param_grid",
]
