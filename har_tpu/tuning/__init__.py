"""Hyperparameter tuning: k-fold cross-validation + parameter grids."""

from har_tpu.tuning.cross_validator import (
    CrossValidator,
    CrossValidatorModel,
    kfold_indices,
    param_grid,
)

__all__ = [
    "CrossValidator",
    "CrossValidatorModel",
    "kfold_indices",
    "param_grid",
]
