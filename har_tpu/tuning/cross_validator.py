"""K-fold cross-validation with grid search.

Replaces MLlib's CrossValidator/ParamGridBuilder (reference
Main/main.py:202-222: 5 folds × 9-point LR grid = 45 fits + a refit).
Where Spark schedules each fit as a separate distributed job, here every
fit is already one compiled XLA program, and independent (fold, param)
fits run back-to-back reusing the same compilation (identical shapes ⇒
one compile, 45 executions).

Reference quirk, reproduced behind a flag: the script passes whatever
evaluator variable was last assigned into each CrossValidator — the MAE
RegressionEvaluator (SURVEY §2 N) — so model selection optimizes MAE over
*label indices*, not accuracy.  ``selection_metric="mae"`` replicates
that; the default is accuracy.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Mapping, Sequence

import numpy as np

from har_tpu.features.wisdm_pipeline import FeatureSet
from har_tpu.ops.metrics import evaluate

# metrics where lower is better
_MINIMIZE = {"mae", "mse", "rmse"}


def param_grid(**grids: Sequence[Any]) -> list[dict[str, Any]]:
    """ParamGridBuilder: cartesian product of value lists.

    param_grid(reg_param=[0.1, 0.3, 0.5], elastic_net_param=[0.0, 0.1, 0.2])
    reproduces the reference's 9-point LR grid (Main/main.py:202-207).
    """
    if not grids:
        return [{}]
    keys = sorted(grids)
    return [
        dict(zip(keys, combo))
        for combo in itertools.product(*(grids[k] for k in keys))
    ]


def kfold_indices(
    n: int, num_folds: int, seed: int
) -> list[tuple[np.ndarray, np.ndarray]]:
    """Seeded shuffle → num_folds (train_idx, val_idx) pairs."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    folds = np.array_split(perm, num_folds)
    out = []
    for i in range(num_folds):
        val = folds[i]
        train = np.concatenate([f for j, f in enumerate(folds) if j != i])
        out.append((train, val))
    return out


@dataclasses.dataclass(frozen=True)
class CrossValidator:
    estimator: Any  # Classifier protocol
    grid: Sequence[Mapping[str, Any]] = (({}),)
    num_folds: int = 5
    selection_metric: str = "accuracy"
    seed: int = 2018

    def fit(self, data: FeatureSet) -> "CrossValidatorModel":
        folds = kfold_indices(len(data), self.num_folds, self.seed)
        grid = list(self.grid) or [{}]
        sign = -1.0 if self.selection_metric in _MINIMIZE else 1.0

        # fast path: estimators exposing a vectorized sweep (the whole
        # grid×fold matrix as a few compiled programs — SURVEY §2c.2's
        # "embarrassingly parallel → vmap") return the score matrix at
        # once; anything else falls back to fit-per-cell
        score_matrix = (
            self.estimator.cv_scores(
                data, folds, grid, self.selection_metric
            )
            if hasattr(self.estimator, "cv_scores")
            else None
        )
        if score_matrix is not None:
            avg_metrics = [float(m) for m in score_matrix.mean(axis=1)]
        else:
            avg_metrics = []
            for params in grid:
                est = (
                    self.estimator.copy_with(**params)
                    if params
                    else self.estimator
                )
                scores = []
                for train_idx, val_idx in folds:
                    model = est.fit(data.take(train_idx))
                    val = data.take(val_idx)
                    preds = model.transform(val)
                    rep = evaluate(val.label, preds.raw, model.num_classes)
                    scores.append(rep[self.selection_metric])
                avg_metrics.append(float(np.mean(scores)))

        best_i = int(np.argmax(sign * np.asarray(avg_metrics)))
        best_params = dict(grid[best_i])
        best_est = (
            self.estimator.copy_with(**best_params)
            if best_params
            else self.estimator
        )
        best_model = best_est.fit(data)  # refit on the full training set
        return CrossValidatorModel(
            best_model=best_model,
            best_params=best_params,
            avg_metrics=avg_metrics,
            grid=[dict(g) for g in grid],
            selection_metric=self.selection_metric,
        )


@dataclasses.dataclass(frozen=True)
class CrossValidatorModel:
    best_model: Any
    best_params: dict[str, Any]
    avg_metrics: list[float]
    grid: list[dict[str, Any]]
    selection_metric: str

    @property
    def num_classes(self) -> int:
        return self.best_model.num_classes

    def transform(self, data) -> Any:
        return self.best_model.transform(data)
