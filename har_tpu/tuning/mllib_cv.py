"""Replay of PySpark's CrossValidator for the bit-exact LR parity lane.

The reference's CV headline (0.7145 — Main/main.py:209-222, result.txt CV
block) comes from ``pyspark.ml.tuning.CrossValidator`` — a pure-Python
driver, not Scala's: it appends a SQL ``rand(seed)`` column to the training
frame, carves fold f as ``f*h <= r < (f+1)*h`` (h = 1/numFolds), fits every
grid candidate per fold, accumulates ``metric / numFolds`` per candidate,
and refits the arg-best candidate on the full frame.  The evaluator it is
handed is the reference's last-assigned RegressionEvaluator — the MAE
quirk (SURVEY §2 N): selection minimizes mean |prediction - label| over
label indices.

Determinism notes:
  - ``rand(seed)`` is Catalyst's Rand: one XORShiftRandom(seed +
    partitionIndex) double per row; the captured run used one partition.
  - The default seed is ``hash('CrossValidator')`` in the *driver's*
    Python.  Under Python 2 (2019-era PySpark) that is the deterministic
    value ``py2_string_hash`` computes, and the selection picks
    (0.1, 0.1) — the candidate whose full-train refit reproduces the CV
    block's 1161/1625 exactly.  Under Python 3 the seed is randomized
    per process; the same candidate wins by a wide MAE margin for most
    seeds (26/30 in a measured sweep, the rest picking (0.1, 0.2)), so
    the committed run is consistent with a py2 driver or a typical py3
    seed.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from har_tpu.data.spark_random import bernoulli_draws, py2_string_hash
from har_tpu.models._jvm_native import CsrMatrix
from har_tpu.models.mllib_lr import MLlibLRModel, fit_mllib_lr

#: The reference grid (Main/main.py:202-207): regParam × elasticNetParam.
REFERENCE_GRID: tuple[dict, ...] = tuple(
    {"reg_param": reg, "elastic_net_param": enp}
    for reg in (0.1, 0.3, 0.5)
    for enp in (0.0, 0.1, 0.2)
)


def default_cv_seed() -> int:
    """pyspark HasSeed default for CrossValidator under Python 2."""
    return py2_string_hash("CrossValidator")


@dataclasses.dataclass(frozen=True)
class MLlibCVResult:
    best_params: dict
    best_index: int
    avg_metrics: tuple[float, ...]
    model: MLlibLRModel  # refit of best_params on the full training frame


def _regression_metric(
    pred: np.ndarray, label: np.ndarray, metric: str
) -> float:
    err = label - pred
    if metric == "mae":
        return float(np.mean(np.abs(err)))
    mse = float(np.mean(err * err))
    if metric == "mse":
        return mse
    if metric == "rmse":
        return float(np.sqrt(mse))
    if metric == "r2":
        ss_tot = float(np.sum((label - label.mean()) ** 2))
        return 1.0 - float(np.sum(err * err)) / ss_tot
    raise ValueError(f"unknown metric {metric!r}")


def mllib_cross_validate(
    x_train: CsrMatrix,
    y_train: np.ndarray,
    grid: Sequence[dict] = REFERENCE_GRID,
    num_folds: int = 5,
    seed: int | None = None,
    metric: str = "mae",
    larger_is_better: bool = False,
    max_iter: int = 20,
) -> MLlibCVResult:
    """CrossValidator._fit over the bit-exact MLlib LR trainer."""
    if seed is None:
        seed = default_cv_seed()
    n = x_train.n_rows
    draws = bernoulli_draws(n, seed)
    h = 1.0 / num_folds
    metrics = [0.0] * len(grid)
    all_rows = np.arange(n)
    for fold in range(num_folds):
        lb = fold * h
        ub = (fold + 1) * h
        val_mask = (draws >= lb) & (draws < ub)
        xt = x_train.take(all_rows[~val_mask])
        xv = x_train.take(all_rows[val_mask])
        yt = y_train[~val_mask]
        yv = y_train[val_mask]
        for j, params in enumerate(grid):
            model = fit_mllib_lr(xt, yt, max_iter=max_iter, **params)
            _, _, pred = model.transform(xv)
            metrics[j] += _regression_metric(pred, yv, metric) / num_folds
    best = (
        int(np.argmax(metrics))
        if larger_is_better
        else int(np.argmin(metrics))
    )
    model = fit_mllib_lr(x_train, y_train, max_iter=max_iter, **grid[best])
    return MLlibCVResult(
        best_params=dict(grid[best]),
        best_index=best,
        avg_metrics=tuple(metrics),
        model=model,
    )
