"""Spark-`show()`-style ASCII tables.

The reference's report is a stdout capture where every DataFrame `.show()`
prints the +---+---+ bordered table (reference result.txt throughout);
this renderer reproduces that format so our result.txt diffs cleanly
against the reference's.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np


def _fmt(v) -> str:
    if isinstance(v, (float, np.floating)):
        # Java Double.toString keeps a trailing .0 on whole doubles
        # ("0.0", "2.0" in result.txt:121-125); Python's float repr does
        # the same shortest-round-trip formatting
        return repr(float(v))
    return str(v)


def show(
    columns: Sequence[str],
    rows: Iterable[Sequence],
    max_rows: int | None = 20,
    truncate: int = 20,
) -> str:
    """Render rows Spark-style; returns the table as a string."""
    rows = [list(r) for r in rows]
    shown = rows if max_rows is None else rows[:max_rows]
    cells = [
        [
            (s if len(s) <= truncate else s[: truncate - 3] + "...")
            for s in map(_fmt, row)
        ]
        for row in shown
    ]
    widths = [
        max(len(str(c)), *(len(r[i]) for r in cells)) if cells else len(str(c))
        for i, c in enumerate(columns)
    ]
    sep = "+" + "+".join("-" * w for w in widths) + "+"
    out = [sep]
    out.append(
        "|" + "|".join(str(c).rjust(w) for c, w in zip(columns, widths)) + "|"
    )
    out.append(sep)
    for r in cells:
        out.append("|" + "|".join(v.rjust(w) for v, w in zip(r, widths)) + "|")
    out.append(sep)
    if max_rows is not None and len(rows) > max_rows:
        out.append(f"only showing top {max_rows} rows")
    return "\n".join(out) + "\n"
