"""Spark-`show()`-style ASCII tables.

The reference's report is a stdout capture where every DataFrame `.show()`
prints the +---+---+ bordered table (reference result.txt throughout);
this renderer reproduces that format so our result.txt diffs cleanly
against the reference's.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np


def _java_double_str(v: float) -> str:
    """Java Double.toString: plain decimal for |v| in [1e-3, 1e7),
    scientific outside ('5.0E-4', '1.2345678E7'), a trailing .0 on whole
    doubles.  Python's repr shares the shortest-round-trip mantissa but
    switches notation at different thresholds and writes exponents
    differently, so parity tables need the Java rules."""
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "Infinity"
    if v == float("-inf"):
        return "-Infinity"
    a = abs(v)
    if a == 0.0:
        return "-0.0" if str(v).startswith("-") else "0.0"
    if 1e-3 <= a < 1e7:
        s = repr(v)  # never scientific in this range
        return s if "." in s else s + ".0"
    # shortest scientific mantissa that round-trips, Java exponent style
    for p in range(1, 18):
        cand = f"{v:.{p}e}"
        if float(cand) == v:
            m, e = cand.split("e")
            m = m.rstrip("0")
            if m.endswith("."):
                m += "0"
            return f"{m}E{int(e)}"
    return repr(v)  # pragma: no cover - p=17 always round-trips


def _fmt(v) -> str:
    if isinstance(v, (float, np.floating)):
        return _java_double_str(float(v))
    return str(v)


def show(
    columns: Sequence[str],
    rows: Iterable[Sequence],
    max_rows: int | None = 20,
    truncate: int = 20,
) -> str:
    """Render rows Spark-style; returns the table as a string."""
    rows = [list(r) for r in rows]
    shown = rows if max_rows is None else rows[:max_rows]
    cells = [
        [
            (s if len(s) <= truncate else s[: truncate - 3] + "...")
            for s in map(_fmt, row)
        ]
        for row in shown
    ]
    widths = [
        max(len(str(c)), *(len(r[i]) for r in cells)) if cells else len(str(c))
        for i, c in enumerate(columns)
    ]
    sep = "+" + "+".join("-" * w for w in widths) + "+"
    out = [sep]
    out.append(
        "|" + "|".join(str(c).rjust(w) for c, w in zip(columns, widths)) + "|"
    )
    out.append(sep)
    for r in cells:
        out.append("|" + "|".join(v.rjust(w) for v, w in zip(r, widths)) + "|")
    out.append(sep)
    if max_rows is not None and len(rows) > max_rows:
        out.append(f"only showing top {max_rows} rows")
    return "\n".join(out) + "\n"
