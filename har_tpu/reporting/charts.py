"""Metric chart artifacts — the Graph.xlsx/Graph.pdf equivalent.

The reference ships hand-made Excel charts over its two metrics CSVs
(Main/wisdm_main_ver_0.0/main_result/{Graph.xlsx, Graph.pdf, Results.xls}
— SURVEY §0 file census: sheet "Graph" holds 8 charts over the CSV
columns).  This module renders the same eight views as PNGs directly
from the CSVs the run just wrote, so every run ships its charts instead
of a one-off spreadsheet:

  1-4  per-classifier Accuracy, F1 Score, Training Time, Testing Time
       (additional_param.csv)
  5-8  the cross-validation variants (crossFold_additional_param.csv)

Chart files are named ``Graph <metric>.png`` / ``Graph CV <metric>.png``.
"""

from __future__ import annotations

import csv
import os
import re

#: (column in the plain CSV, column in the CV CSV, filename stem)
_CHARTS = (
    ("Accuracy", "Cross Fold Accuracy", "Accuracy"),
    ("F1 Score", "F1 Score", "F1 Score"),
    ("Training Time", "Cross Validation Training Time", "Training Time"),
    ("Testing Time", "Cross Validation Testing Time", "Testing Time"),
)


def _short_name(classifier: str) -> str:
    """Compact estimator label from the CSV's Classifier repr."""
    m = re.match(r"([A-Za-z]+?)(?:Classification)?(?:Model)?_", classifier)
    if m:
        return m.group(1)
    return classifier.split(" ")[0][:24] or classifier[:24]


def _read_rows(csv_path: str) -> list[dict]:
    with open(csv_path, newline="") as f:
        rows = list(csv.DictReader(f))
    # the reference appends runs (append-mode quirk); chart the LAST run
    # by dropping repeated header rows and keeping the trailing block
    return [r for r in rows if r.get("Classifier") != "Classifier"]


def save_metric_charts(
    csv_path: str | None,
    cv_csv_path: str | None,
    out_dir: str,
) -> list[str]:
    """Render the 8 chart PNGs; returns the files written (those whose
    source CSV exists).  Returns [] when matplotlib (the `plots` extra)
    is not installed — chart artifacts are optional, runs must not die
    after training because a plotting dependency is absent."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return []

    os.makedirs(out_dir, exist_ok=True)
    written: list[str] = []
    for path, prefix in ((csv_path, ""), (cv_csv_path, "CV ")):
        if path is None or not os.path.exists(path):
            continue
        rows = _read_rows(path)
        if not rows:
            continue
        names = [_short_name(r["Classifier"]) for r in rows]
        for plain_col, cv_col, stem in _CHARTS:
            col = cv_col if prefix else plain_col
            try:
                values = [float(r[col]) for r in rows]
            except (KeyError, ValueError):
                continue
            fig, ax = plt.subplots(figsize=(6, 4))
            ax.bar(names, values, color="#4C72B0")
            ax.set_title(f"{prefix}{stem} by Classifier")
            ax.set_ylabel(
                f"{stem} (s)" if "Time" in stem else stem
            )
            ax.tick_params(axis="x", labelrotation=20)
            for i, v in enumerate(values):
                ax.annotate(
                    f"{v:.4g}",
                    (i, v),
                    ha="center",
                    va="bottom",
                    fontsize=8,
                )
            fig.tight_layout()
            out = os.path.join(out_dir, f"Graph {prefix}{stem}.png")
            fig.savefig(out, dpi=110)
            plt.close(fig)
            written.append(out)
    return written
