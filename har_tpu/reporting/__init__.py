"""Run reports, metrics CSVs, and EDA plots (reference-format artifacts)."""

from har_tpu.reporting.ascii_table import show
from har_tpu.reporting.report import (
    CSV_HEADER,
    CV_CSV_HEADER,
    ModelResult,
    ReportWriter,
)

__all__ = [
    "show",
    "CSV_HEADER",
    "CV_CSV_HEADER",
    "ModelResult",
    "ReportWriter",
]
