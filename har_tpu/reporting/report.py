"""result.txt-style run report + metrics CSV writers.

Reproduces the reference's three artifacts (SURVEY §5.5):
  - ``result.txt``: the full run log — schema, sample rows, class counts,
    summary stats, per-model evaluation blocks (reference redirects
    sys.stdout to this file, Main/main.py:11-12; we write it explicitly).
  - ``additional_param.csv``: per-classifier summary row with the exact
    reference header (Main/main.py:657).
  - ``crossFold_additional_param.csv``: CV variant (Main/main.py:671).

The reference opens its CSVs in append mode and rewrites the header every
run (a quirk that accumulates junk); we default to truncate-and-write but
keep ``append=True`` for byte-level behavioral parity.
"""

from __future__ import annotations

import csv
import dataclasses
import io
import os
from typing import Any, Mapping, Sequence

import numpy as np

from har_tpu.data.table import Table
from har_tpu.reporting.ascii_table import show

CSV_HEADER = [
    "Classifier",
    "Count Total",
    "Correct",
    "Wrong",
    "Ratio Wrong",
    "Ratio Correct",
    "F1 Score",
    "Training Time",
    "Testing Time",
    "Accuracy",
]

CV_CSV_HEADER = [
    "Classifier",
    "Count Total",
    "Correct",
    "Wrong",
    "Ratio Wrong",
    "Ratio Correct",
    "F1 Score",
    "Cross Validation Training Time",
    "Cross Validation Testing Time",
    "Cross Fold Accuracy",
]


@dataclasses.dataclass
class ModelResult:
    """Everything one CLASSIFICATION AND EVALUATION block needs."""

    name: str
    metrics: Mapping[str, Any]  # output of har_tpu.ops.metrics.evaluate
    train_time_s: float
    test_time_s: float
    is_cv: bool = False
    # Spark-style model line for the report block (result.txt:141,186,231,
    # 276), e.g. "LogisticRegression_<uid>"; falls back to `name`
    display_name: str | None = None

    @property
    def counts(self) -> tuple[int, int, int]:
        cm = np.asarray(self.metrics["confusion_matrix"])
        total = int(cm.sum())
        correct = int(np.trace(cm))
        return total, correct, total - correct


def _welford(values: np.ndarray) -> tuple[float, float]:
    """Catalyst-order mean/sample-variance, row order preserved.

    Spark's describe() evaluates SQL ``avg`` (a plain sequential running
    sum over the rows, divided at the end) and ``stddev_samp`` (Welford's
    central-moment update per row); numpy's pairwise summation differs in
    the last ulps.  The golden result.txt diff is byte-exact only with
    the same accumulation order."""
    total = 0.0
    avg = 0.0
    m2 = 0.0
    n = 0
    for v in values:
        v = float(v)
        n += 1
        total += v
        delta = v - avg
        delta_n = delta / n
        avg += delta_n
        # Catalyst's exact expression (delta * (delta - deltaN)) — the
        # algebraic twin delta*(v - newAvg) rounds differently in the
        # last ulp and breaks the byte-exact diff
        m2 += delta * (delta - delta_n)
    return total / max(n, 1), (m2 / (n - 1) if n > 1 else float("nan"))


def _guava_partition(values: list, left: int, right: int,
                     pivot_index: int, cmp) -> int:
    pivot_value = values[pivot_index]
    values[pivot_index] = values[right]
    values[right] = pivot_value
    store = left
    for i in range(left, right):
        if cmp(values[i], pivot_value) < 0:
            values[store], values[i] = values[i], values[store]
            store += 1
    values[store], values[right] = values[right], values[store]
    return store


def _guava_least_of(items, k: int, cmp) -> list:
    """guava ``Ordering.leastOf(iterator, k)`` — the top-k kernel behind
    Spark's TakeOrderedAndProject (``show`` after ``orderBy``).

    Clean-room port of the published algorithm: a 2k buffer, a threshold
    that skips elements sorting at-or-after it, quickselect trims when the
    buffer fills (which permute tied elements — semantics the report's
    sample tables depend on), and a final stable sort of the buffer.
    """
    import functools

    it = iter(items)
    try:
        first = next(it)
    except StopIteration:
        return []
    if k == 0:
        return []
    buffer_cap = k * 2
    buf = [first]
    threshold = first
    while len(buf) < k:
        try:
            e = next(it)
        except StopIteration:
            break
        buf.append(e)
        if cmp(e, threshold) > 0:  # threshold = max(threshold, e)
            threshold = e
    for e in it:
        if cmp(e, threshold) >= 0:
            continue
        buf.append(e)
        if len(buf) == buffer_cap:
            left, right = 0, buffer_cap - 1
            min_threshold_position = 0
            while left < right:
                pivot_index = (left + right + 1) >> 1
                pivot_new_index = _guava_partition(
                    buf, left, right, pivot_index, cmp
                )
                if pivot_new_index > k:
                    right = pivot_new_index - 1
                elif pivot_new_index < k:
                    left = max(pivot_new_index, left + 1)
                    min_threshold_position = pivot_new_index
                else:
                    break
            del buf[k:]
            threshold = buf[min_threshold_position]
            for i in range(min_threshold_position + 1, k):
                if cmp(buf[i], threshold) > 0:
                    threshold = buf[i]
    buf.sort(key=functools.cmp_to_key(cmp))  # stable, like Arrays.sort
    return buf[:k]


class ReportWriter:
    """Accumulates the run log in memory; `save()` writes the artifacts."""

    def __init__(
        self,
        output_dir: str,
        class_names: Sequence[str] | None = None,
        reference_quirks: bool = False,
    ):
        self.output_dir = output_dir
        self.class_names = list(class_names) if class_names else None
        # True → replicate the reference's output bugs byte-for-byte
        # (the MSE label prints the rmse variable, Main/main.py:171) and
        # omit the per-class extras, for the golden parity artifact
        self.reference_quirks = reference_quirks
        self._buf = io.StringIO()
        self.results: list[ModelResult] = []

    # Dash/equals counts of the reference's print literals, preserved
    # byte-for-byte (they are inconsistent in Main/main.py and the golden
    # diff pins them): header -> dash count, banner -> (left, right).
    _HEADER_DASHES = {
        "Data Schema": 60,
        "Sample Data": 60,
        "Activity Count": 58,
        "Summary": 63,
        "Model Pipeline Schema": 60,
        "Sample Feature Data": 60,
    }
    _BANNER_PADS = {
        "MODELING PIPELINE": (27, 30),
        "TRAINING AND TESTING": (27, 30),
        "CLASSIFICATION AND EVALUATION": (28, 28),
    }

    # --- low-level -------------------------------------------------------
    def line(self, text: str = "") -> None:
        self._buf.write(text + "\n")

    def header(self, title: str, width: int = 74, fill: str = "-") -> None:
        dashes = self._HEADER_DASHES.get(title)
        if dashes is None:
            dashes = max(0, width - len(title))
        self.line(title + fill * dashes)

    def banner(self, title: str, pad: str = "=") -> None:
        left, right = self._BANNER_PADS.get(title, (27, 30))
        self.line(f"{pad * left}{title}{pad * right}")

    # --- sections matching the reference layout --------------------------
    def schema(self, table: Table) -> None:
        """Spark printSchema() block (reference result.txt:2-18)."""
        self.header("Data Schema")
        self.line("root")
        for name, ctype in zip(table.schema.names, table.schema.types):
            self.line(f" |-- {name}: {ctype.spark_name} (nullable = true)")
        self.line()

    def sample(self, table: Table, n: int = 5) -> None:
        self.header("Sample Data")
        cols = table.column_names
        rows = list(zip(*(table[c][:n] for c in cols)))
        self.line(show(cols, rows, max_rows=n) + f"only showing top {n} rows")
        self.line()

    def class_counts(self, labels: Sequence[str]) -> None:
        self.header("Activity Count", fill="-")
        vals, counts = np.unique(np.asarray(labels), return_counts=True)
        order = np.argsort(-counts)
        rows = [(vals[i], int(counts[i])) for i in order]
        self.line(show(["activity", "count"], rows, max_rows=None))

    def summary(self, table: Table) -> None:
        """describe().toPandas().transpose() block (result.txt:44-57).

        The reference prints the transposed pandas frame of Spark's
        describe() (Main/main.py:43): a 0..4 column-label row, a
        'summary' row naming the statistics, then one row per numeric
        column with count/mean/stddev as full-precision doubles and
        min/max rendered in the column's own dtype."""
        import pandas as pd

        self.header("Summary", fill="-")
        data: dict[str, list[str]] = {
            "summary": ["count", "mean", "stddev", "min", "max"]
        }
        for name in table.column_names:
            col = np.asarray(table[name])
            if not np.issubdtype(col.dtype, np.number):
                continue
            is_int = np.issubdtype(col.dtype, np.integer)
            fmt = (
                (lambda v: str(int(v)))
                if is_int
                else (lambda v: repr(float(v)))
            )
            mean, var = _welford(col.astype(np.float64))
            data[name] = [
                str(len(col)),
                repr(float(mean)),
                repr(float(np.sqrt(var))),
                fmt(col.min()),
                fmt(col.max()),
            ]
        with pd.option_context(
            "display.width", 80,
            "display.max_columns", None,
            "display.max_rows", None,
            "display.expand_frame_repr", True,
        ):
            self.line(str(pd.DataFrame(data).transpose()))
        self.line()

    def pipeline_schema(self, table: Table) -> None:
        """MODELING PIPELINE printSchema block (result.txt:59-79): the
        transformed dataframe's columns — label + features vector +
        every original column the reference reselects (Main/main.py:74)."""
        self.banner("MODELING PIPELINE")
        self.line()
        self.header("Model Pipeline Schema")
        self.line("root")
        self.line(" |-- label: double (nullable = false)")
        self.line(" |-- features: vector (nullable = true)")
        for name, ctype in zip(table.schema.names, table.schema.types):
            self.line(f" |-- {name}: {ctype.spark_name} (nullable = true)")
        self.line()

    def sample_feature_data(
        self, table: Table, labels, features, n: int = 5
    ) -> None:
        """pandas-repr sample of the transformed frame (result.txt:81-101):
        the reference prints pd.DataFrame(df.take(5)) — label, the dense
        feature tuple (pandas-truncated), then the original columns."""
        import pandas as pd

        self.header("Sample Feature Data")
        data: dict[str, Any] = {
            "label": [float(v) for v in labels[:n]],
            "features": [
                "(" + ", ".join(repr(float(v)) for v in row) + ")"
                for row in np.asarray(features[:n])
            ],
        }
        for name in table.column_names:
            data[name] = list(table[name][:n])
        with pd.option_context(
            "display.width", 80,
            "display.max_colwidth", 50,
            "display.max_columns", None,  # wrap, don't elide columns
            "display.expand_frame_repr", True,
        ):
            self.line(str(pd.DataFrame(data)))
        self.line()

    @staticmethod
    def _sparse_vector_str(row: np.ndarray) -> str:
        """Spark SparseVector str: '(3100,[i...],[v...])' (result.txt:110)."""
        nz = np.nonzero(row)[0]
        idx = ",".join(str(int(i)) for i in nz)
        vals = ",".join(repr(float(row[i])) for i in nz)
        return f"({len(row)},[{idx}],[{vals}])"

    # columns the reference hides from the train/test sample tables
    # (minimized_view, Main/main.py:88) and the ones it drops from
    # test_data (skipped, Main/main.py:94-98)
    _MINIMIZED_VIEW = (
        "XPEAK", "YPEAK", "ZPEAK", "XABSDEV", "YABSDEV", "ZABSDEV",
    )

    def split_sample_tables(
        self, table: Table, features, labels, train_rows, test_rows, n=5
    ) -> None:
        """train/test/test_data show(5) tables (result.txt:107-138).

        ``train_rows``/``test_rows`` are original-table row indices in
        sampled-stream order, so with the spark-exact split the shown
        rows equal the reference's byte-for-byte."""
        shown_cols = [
            c for c in table.column_names if c not in self._MINIMIZED_VIEW
        ]

        def rows_for(indices, cols):
            out = []
            for i in indices[:n]:
                row = [
                    f"{float(labels[i]):.1f}",
                    self._sparse_vector_str(np.asarray(features[i])),
                ]
                for c in cols:
                    row.append(table[c][i])
                out.append(row)
            return out

        for indices, cols in (
            (train_rows, shown_cols),
            (test_rows, shown_cols),
            (test_rows, ["UID"]),  # test_data keeps label+features+UID
        ):
            self.line(
                show(
                    ["label", "features"] + list(cols),
                    rows_for(indices, cols),
                    max_rows=None,
                    truncate=20,
                )
                + (f"only showing top {n} rows" if len(indices) > n else "")
            )
            self.line()

    def split_counts(self, n_train: int, n_test: int) -> None:
        self.banner("TRAINING AND TESTING")
        self.line()
        self.line(f"Training Dataset Count : {n_train}")
        self.line(f"Test Dataset Count     : {n_test}")

    def prediction_sample(
        self, test, preds, class_id: int | None = None, n: int = 5
    ) -> str:
        """The reference's top-n predicted-class sample (Main/main.py:127-130):
        rows predicted as ``class_id`` (default: the last class, as the LR
        block filters prediction==5), ordered by descending probability,
        rendered as the Spark ``show()`` table in result.txt:144-153.
        Returns the table text for model_block to place after the timings.
        """
        probs = np.asarray(preds.probability, np.float64)
        pred = np.asarray(preds.prediction)
        k = int(probs.shape[1] - 1 if class_id is None else class_id)
        idx = np.nonzero(pred == k)[0]
        if idx.size == 0:  # class never predicted: fall back to all rows
            idx = np.arange(len(pred))
        truncated = idx.size > n
        # Spark's `.orderBy("probability", ascending=False).show(n)` is
        # planned as TakeOrderedAndProject over take(n+1): guava
        # Ordering.leastOf with a 2k buffer whose quickselect trims
        # permute TIED rows (equal probability vectors) away from stream
        # order — result.txt's DT sample order is that permutation, so
        # the faithful top-k replay is load-bearing (for distinct keys it
        # reduces to the lexicographic sort).  Vectors compare as their
        # struct, i.e. values arrays lexicographically, descending.
        def cmp(a: int, b: int) -> int:
            pa, pb = probs[a], probs[b]
            for x, y in zip(pa, pb):
                if x != y:
                    return -1 if x > y else 1
            return 0

        order = _guava_least_of(list(idx), n + 1, cmp)[:n]
        uid = getattr(test, "uid", None)
        rows = []
        for i in order:
            vec = "[" + ",".join(repr(float(v)) for v in probs[i]) + "]"
            rows.append(
                [
                    int(uid[i]) if uid is not None else int(i),
                    vec,
                    f"{float(test.label[i]):.1f}",
                    f"{float(pred[i]):.1f}",
                ]
            )
        table = show(
            ["UID", "probability", "label", "prediction"],
            rows,
            max_rows=None,
            truncate=30,
        )
        # Spark's show() prints the footer only when rows were cut off
        if truncated:
            table += f"only showing top {n} rows\n"
        return table

    def model_block(
        self, result: ModelResult, sample_text: str | None = None
    ) -> None:
        """One CLASSIFICATION AND EVALUATION block (result.txt LR block)."""
        if not self.results:
            if not self._buf.getvalue().endswith("\n\n"):
                self.line()  # result.txt:139 — blank before the banner
            self.banner("CLASSIFICATION AND EVALUATION")
        self.results.append(result)
        m = result.metrics
        self.line(result.display_name or result.name)
        self.line(f"Classifier trained in {result.train_time_s:.3f} seconds")
        self.line(f"Prediction made in {result.test_time_s:.3f} seconds")
        if sample_text is not None:
            self._buf.write(sample_text)
        self.line()
        self.line()  # result.txt:154-155 — two blanks after the sample
        self.line("-----------Binary Classification Evaluator-------------")
        self.line()
        # the reference evaluates the Binary evaluator's default metric
        # (areaUnderROC) under this label (result.txt:158,160 are equal)
        self.line(
            f"Binary Classifier Raw Prediction ------------: {m['areaUnderROC']:.6g}"
        )
        self.line(
            f"Binary Clasifier Area Under PR --------------: {m['areaUnderPR']:.6g}"
        )
        self.line(
            f"Binary Clasifier Area Under ROC -------------: {m['areaUnderROC']:.6g}"
        )
        self.line()
        self.line("-----------MultiClass Classification Evaluaton---------")
        self.line()
        self.line(f"MultiClass F1 -------------------------------: {m['f1']:.6g}")
        self.line(
            f"MultiClass Weighted Precision ---------------: {m['weightedPrecision']:.6g}"
        )
        self.line(
            f"MultiClass Weighted Recall ------------------: {m['weightedRecall']:.6g}"
        )
        self.line(
            f"MultiClass Accuracy -------------------------: {m['accuracy']:.6g}"
        )
        self.line()
        self.line("----------------Regression Evaluator-------------------")
        self.line()
        self.line(
            f"Root Mean Squared Error (RMSE) on test data -: {m['rmse']:.6g}"
        )
        # the reference prints the rmse variable under the MSE label
        # (Main/main.py:171 bug); we print the real mse unless the
        # caller asked for the byte-parity artifact
        mse_shown = m["rmse"] if self.reference_quirks else m["mse"]
        self.line(f"Mean Squared Error on test data -------------: {mse_shown:.6g}")
        self.line(f"R^2 metric on test data ---------------------: {m['r2']:.6g}")
        self.line(f"Mean Absolute Error on test data ------------: {m['mae']:.6g}")
        self.line()
        self.line("------------------Additional Factors--------------------")
        self.line()
        total, correct, wrong = result.counts
        self.line(f"Total Count          = {total}")
        self.line(f"Total Correct        = {correct}")
        self.line(f"Total Wrong          = {wrong}")
        self.line(f"Wrong Ratio          = {wrong / max(total, 1):.6g}")
        self.line(f"Right Ratio          = {correct / max(total, 1):.6g}")
        self.line()
        # the reference block ends here (result.txt:184); the per-class
        # extras are a framework addition placed after the terminator so
        # the block shape still diffs cleanly against the reference's
        self.line("*" * 57)
        self.line()
        if not self.reference_quirks:
            self._per_class_block(m)

    def _per_class_block(self, m: Mapping[str, Any]) -> None:
        """Per-class precision/recall/F1 + the confusion matrix — a
        framework extra beyond the reference's aggregate-only battery
        (its evaluators never expose per-class numbers)."""
        if "precision_per_class" not in m or "confusion_matrix" not in m:
            return
        cm = np.asarray(m["confusion_matrix"])
        k = len(cm)
        self.line("------------------Per-Class Metrics---------------------")
        self.line()
        names = (
            self.class_names
            if self.class_names and len(self.class_names) == k
            else [str(c) for c in range(k)]
        )
        rows = [
            [
                names[c],
                int(cm[c].sum()),
                f"{m['precision_per_class'][c]:.4f}",
                f"{m['recall_per_class'][c]:.4f}",
                f"{m['f1_per_class'][c]:.4f}",
            ]
            for c in range(k)
        ]
        self._buf.write(
            show(
                ["class", "support", "precision", "recall", "f1"],
                rows,
                max_rows=None,
            )
        )
        self._buf.write(
            show(
                ["true\\pred"] + list(names),
                [[names[c]] + [int(v) for v in cm[c]] for c in range(k)],
                max_rows=None,
            )
        )
        self.line()

    # --- artifacts -------------------------------------------------------
    def text(self) -> str:
        return self._buf.getvalue()

    def save(self, append_csv: bool = False) -> dict[str, str]:
        os.makedirs(self.output_dir, exist_ok=True)
        paths = {}
        paths["result"] = os.path.join(self.output_dir, "result.txt")
        with open(paths["result"], "w") as f:
            f.write(self.text())

        plain = [r for r in self.results if not r.is_cv]
        cv = [r for r in self.results if r.is_cv]
        mode = "a" if append_csv else "w"
        if plain:
            paths["csv"] = os.path.join(self.output_dir, "additional_param.csv")
            self._write_csv(paths["csv"], CSV_HEADER, plain, mode)
        if cv:
            paths["cv_csv"] = os.path.join(
                self.output_dir, "crossFold_additional_param.csv"
            )
            self._write_csv(paths["cv_csv"], CV_CSV_HEADER, cv, mode)
        return paths

    @staticmethod
    def _write_csv(path, header, results, mode):
        with open(path, mode, newline="") as f:
            w = csv.writer(f)
            w.writerow(header)
            for r in results:
                total, correct, wrong = r.counts
                m = r.metrics
                w.writerow(
                    [
                        # the reference writes the model object's repr
                        # (Main/main.py:660: 'Classifier': lrModel) —
                        # display_name is our uid-stable equivalent
                        r.display_name or r.name,
                        total,
                        correct,
                        wrong,
                        wrong / max(total, 1),
                        correct / max(total, 1),
                        m["f1"],
                        r.train_time_s,
                        r.test_time_s,
                        m["accuracy"],
                    ]
                )
