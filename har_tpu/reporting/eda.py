"""EDA plots: hexbin feature-pair grid + scatter matrix.

Reproduces the reference's plot tail (Main/main.py:686-710, standalone in
matplot.py): a 10% sample of the numeric features, hexbin plots for every
ordered feature pair saved as ``Fig <X>_<Y>.png``, plus a scatter matrix
(the reference's `Scatter_Matrix.png` step never completed in the shipped
artifacts — SURVEY §2 Q — but the code path exists, so ours does too).
"""

from __future__ import annotations

import os

import numpy as np


def save_eda_plots(
    table,
    numeric_columns,
    output_dir: str,
    sample_fraction: float = 0.1,
    seed: int = 2018,
    pairs: str = "distinct",
) -> list[str]:
    """Write hexbin pair plots + scatter matrix; returns saved paths.

    ``pairs='distinct'`` writes only X≠Y pairs like the reference's loop
    effectively does (identical-pair hexbins are degenerate diagonals).
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(output_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    n = len(table)
    take = rng.random(n) <= sample_fraction
    data = {c: np.asarray(table[c], np.float64)[take] for c in numeric_columns}

    paths = []
    for xcol in numeric_columns:
        for ycol in numeric_columns:
            if pairs == "distinct" and xcol == ycol:
                continue
            fig, ax = plt.subplots(figsize=(4, 3))
            ax.hexbin(data[xcol], data[ycol], gridsize=25, cmap="viridis")
            ax.set_xlabel(xcol)
            ax.set_ylabel(ycol)
            path = os.path.join(output_dir, f"Fig {xcol}_{ycol}.png")
            fig.savefig(path, dpi=72)
            plt.close(fig)
            paths.append(path)

    # scatter matrix over the sampled numeric features
    k = len(numeric_columns)
    fig, axes = plt.subplots(k, k, figsize=(2 * k, 2 * k))
    for i, ycol in enumerate(numeric_columns):
        for j, xcol in enumerate(numeric_columns):
            ax = axes[i, j] if k > 1 else axes
            if i == j:
                ax.hist(data[xcol], bins=20)
            else:
                ax.plot(data[xcol], data[ycol], ".", markersize=1)
            ax.set_xticks([])
            ax.set_yticks([])
            if j == 0:
                ax.set_ylabel(ycol, fontsize=6)
            if i == k - 1:
                ax.set_xlabel(xcol, fontsize=6)
    path = os.path.join(output_dir, "Scatter_Matrix.png")
    fig.savefig(path, dpi=72)
    plt.close(fig)
    paths.append(path)
    return paths
