from har_tpu.utils.profiling import StepTimer, trace, write_timing_csv

__all__ = ["StepTimer", "trace", "write_timing_csv"]
