"""Model-FLOPs-utilization accounting for bench lanes.

The reference publishes wall-clock only (SURVEY §6); windows/s is the
apples-to-apples headline, but it can't say whether a lane is
compute-bound or dispatch-bound.  These helpers turn the trainer's
XLA-reported program flop count (TrainerConfig.compute_flops →
history["program_flops"]) into achieved FLOP/s and a fraction of the
chip's peak — the "is it actually fast" number VERDICT r1 asked for.
"""

from __future__ import annotations

import jax

# Peak dense bf16/fp16 matmul throughput per chip, FLOP/s.  Keys are
# matched as substrings of jax's Device.device_kind, FIRST match wins —
# keep more specific keys (e.g. "v5 lite") before their prefixes ("v5");
# values from Google's published per-chip specs.
_PEAK_BY_KIND = (
    ("v6 lite", 918e12),  # Trillium / v6e
    ("v6e", 918e12),
    ("v5 lite", 197e12),  # v5e
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def chip_peak_flops(device=None) -> float | None:
    """Peak bf16 FLOP/s of one chip, or None when unknown (e.g. CPU)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and jax.default_backend() != "tpu":
        return None
    for key, peak in _PEAK_BY_KIND:
        if key in kind:
            return peak
    return None


def chip_state_probe(n: int = 4096, iters: int = 200, reps: int = 3):
    """{matmul_tflops, pct_of_peak} from a pure bf16 matmul chain.

    Isolates the chip from every framework concern (no input pipeline,
    optimizer, or dispatch-amortization question): a healthy chip lands
    at 85-95% of peak; meaningfully below that, the session's bench
    draws are state-limited, not code-limited (the remote chip/tunnel
    has session-scale states — pure-matmul draws from 90% of peak down
    to 7% observed within one day).  Best of ``reps`` timed runs; None
    on failure.  pct_of_peak is None when the chip's peak is unknown —
    that means "cannot judge", not "degraded".
    """
    import time

    import jax.numpy as jnp
    import numpy as np

    try:
        x = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
        f = jax.jit(
            lambda x: jax.lax.fori_loop(0, iters, lambda _, a: a @ x, x)
        )
        np.asarray(f(x))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            np.asarray(f(x))
            best = min(best, time.perf_counter() - t0)
    except Exception:
        return None
    flops = iters * 2 * n**3
    peak = chip_peak_flops()
    return {
        "matmul_tflops": round(flops / best / 1e12, 1),
        "pct_of_peak": (
            round(100 * flops / best / peak, 1) if peak else None
        ),
    }


def steady_state_fit(
    t_short: float, t_full: float, steps_short: int, steps_full: int
) -> tuple[float, float]:
    """(step_seconds, dispatch_overhead_seconds) from two fit timings.

    The two-point split: slope = in-program step time, intercept = fixed
    dispatch/transfer latency.  The single definition shared by bench.py's
    neural_lane and scripts/mfu_tune.py so the bench's steady MFU and the
    tuning sweep's can never drift apart.
    """
    step_s = max(
        (t_full - t_short) / max(steps_full - steps_short, 1), 1e-9
    )
    overhead_s = max(t_short - steps_short * step_s, 0.0)
    return step_s, overhead_s


def mfu_fields(
    prefix: str, history: dict, peak: float | None
) -> dict[str, float]:
    """{prefix}_achieved_tflops / {prefix}_mfu_pct from a fit history.

    Achieved FLOP/s = the compiled program's XLA flop count over the
    measured train time; MFU = achieved / chip peak.  Empty when the
    trainer didn't record program_flops.
    """
    flops = history.get("program_flops")
    t = history.get("train_time_s")
    if not flops or not t:
        return {}
    achieved = flops / t
    out = {f"{prefix}_achieved_tflops": round(achieved / 1e12, 3)}
    if peak:
        out[f"{prefix}_mfu_pct"] = round(100.0 * achieved / peak, 2)
    return out
