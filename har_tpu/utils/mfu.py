"""Model-FLOPs-utilization accounting for bench lanes.

The reference publishes wall-clock only (SURVEY §6); windows/s is the
apples-to-apples headline, but it can't say whether a lane is
compute-bound or dispatch-bound.  These helpers turn the trainer's
XLA-reported program flop count (TrainerConfig.compute_flops →
history["program_flops"]) into achieved FLOP/s and a fraction of the
chip's peak — the "is it actually fast" number VERDICT r1 asked for.
"""

from __future__ import annotations

import jax

# Peak dense bf16/fp16 matmul throughput per chip, FLOP/s.  Keys are
# matched as substrings of jax's Device.device_kind, FIRST match wins —
# keep more specific keys (e.g. "v5 lite") before their prefixes ("v5");
# values from Google's published per-chip specs.
_PEAK_BY_KIND = (
    ("v6 lite", 918e12),  # Trillium / v6e
    ("v6e", 918e12),
    ("v5 lite", 197e12),  # v5e
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def chip_peak_flops(device=None) -> float | None:
    """Peak bf16 FLOP/s of one chip, or None when unknown (e.g. CPU)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and jax.default_backend() != "tpu":
        return None
    for key, peak in _PEAK_BY_KIND:
        if key in kind:
            return peak
    return None


# Module-level so the probe-decomposition test can inject a slow tunnel
# (monkeypatching this is how a degraded device→host path is simulated
# without degraded hardware).
def _host_fetch(buf):
    import numpy as np

    return np.asarray(buf)


# Decomposed-probe degradation thresholds.  compute: bench.HEALTHY_CHIP_PCT
# is the gate; these two name the OTHER resources when a draw is slow.
# Observed states: the r5 committed draw's 32 MB fetch implied ~20 MB/s
# (VERDICT r5), healthy sessions move bulk arrays at hundreds of MB/s;
# dispatch RTT through the tunnel was ~100 ms degraded (serving
# device_p50_ms 99.6 at batch 1) vs single-digit ms healthy.
TUNNEL_HEALTHY_MB_S = 100.0
DISPATCH_HEALTHY_RTT_MS = 25.0


def chip_state_probe(n: int = 4096, iters: int = 200, reps: int = 3):
    """Three-number chip/tunnel/dispatch decomposition of device state.

    Isolates the chip from every framework concern (no input pipeline,
    optimizer, or dispatch-amortization question) — and, since r6, from
    the *tunnel*: the compute interval is timed with
    ``jax.block_until_ready`` on the device buffer, so the measured
    window contains no device→host fetch.  (The pre-r6 probe timed
    ``np.asarray(f(x))`` — a 32 MB fetch through a degraded tunnel
    starved the ≥25% healthy gate by construction: the committed r5 draw
    probed "3.9% of peak" while its own saturation lane sustained 33.6%
    MFU in-program.  VERDICT r5 item 1.)

    Returns a dict with three independently-timed numbers, or None when
    the probe cannot run at all:
      compute_pct / pct_of_peak — pure bf16 matmul chain, device-only
          timing; a healthy chip lands at 85-95% of peak.  None when the
          chip's peak is unknown — "cannot judge", not "degraded".
      tunnel_mb_s — device→host bandwidth from a timed fetch of the
          known-size (n, n) bf16 result buffer.
      dispatch_rtt_ms — round-trip of a no-op dispatch (tiny jitted add,
          timed to completion): the fixed per-call latency every lane's
          end-to-end number pays.
    Best of ``reps`` timed runs for each interval.
    """
    import time

    import jax.numpy as jnp

    out = {}
    try:
        x = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
        f = jax.jit(
            lambda x: jax.lax.fori_loop(0, iters, lambda _, a: a @ x, x)
        )
        jax.block_until_ready(f(x))  # compile + warm
        best = float("inf")
        result = None
        for _ in range(reps):
            t0 = time.perf_counter()
            result = jax.block_until_ready(f(x))
            best = min(best, time.perf_counter() - t0)
    except Exception:
        return None
    flops = iters * 2 * n**3
    peak = chip_peak_flops()
    compute_pct = round(100 * flops / best / peak, 1) if peak else None
    out = {
        # 3 decimals: a CPU fallback probe (tests; no chip peak) runs
        # tiny shapes whose TFLOPs live below the 0.1 rounding grain
        "matmul_tflops": round(flops / best / 1e12, 3),
        # compute-only %-of-peak under BOTH names: pct_of_peak is what
        # every existing gate/log reads; compute_pct is the explicit
        # name alongside tunnel_mb_s / dispatch_rtt_ms
        "pct_of_peak": compute_pct,
        "compute_pct": compute_pct,
    }
    try:  # tunnel: timed fetch of the known-size result buffer
        n_bytes = result.size * result.dtype.itemsize
        t_fetch = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            _host_fetch(result)
            t_fetch = min(t_fetch, time.perf_counter() - t0)
        out["tunnel_mb_s"] = round(n_bytes / 1e6 / max(t_fetch, 1e-9), 1)
    except Exception:
        out["tunnel_mb_s"] = None
    try:  # dispatch RTT: no-op-sized program, timed to completion
        tiny = jnp.zeros((8, 128), jnp.bfloat16)
        g = jax.jit(lambda a: a + 1)
        jax.block_until_ready(g(tiny))  # compile + warm
        t_rtt = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(g(tiny))
            t_rtt = min(t_rtt, time.perf_counter() - t0)
        out["dispatch_rtt_ms"] = round(t_rtt * 1e3, 2)
    except Exception:
        out["dispatch_rtt_ms"] = None
    return out


def degraded_resource(
    probe: dict | None, healthy_compute_pct: float = 25.0
) -> str | None:
    """Name which resource(s) a probe decomposition shows degraded.

    Returns a human-readable clause for the bench draw's note, or None
    when nothing in the probe crosses its threshold (compute below
    ``healthy_compute_pct``, tunnel below TUNNEL_HEALTHY_MB_S, dispatch
    above DISPATCH_HEALTHY_RTT_MS).
    """
    if not probe:
        return None
    parts = []
    pct = probe.get("compute_pct", probe.get("pct_of_peak"))
    if pct is not None and pct < healthy_compute_pct:
        parts.append(f"chip compute ({pct}% of bf16 peak)")
    mbs = probe.get("tunnel_mb_s")
    if mbs is not None and mbs < TUNNEL_HEALTHY_MB_S:
        parts.append(f"device→host tunnel ({mbs} MB/s)")
    rtt = probe.get("dispatch_rtt_ms")
    if rtt is not None and rtt > DISPATCH_HEALTHY_RTT_MS:
        parts.append(f"dispatch RTT ({rtt} ms)")
    return ", ".join(parts) or None


def steady_state_fit(
    t_short: float, t_full: float, steps_short: int, steps_full: int
) -> tuple[float, float]:
    """(step_seconds, dispatch_overhead_seconds) from two fit timings.

    The two-point split: slope = in-program step time, intercept = fixed
    dispatch/transfer latency.  The single definition shared by bench.py's
    neural_lane and scripts/mfu_tune.py so the bench's steady MFU and the
    tuning sweep's can never drift apart.
    """
    step_s = max(
        (t_full - t_short) / max(steps_full - steps_short, 1), 1e-9
    )
    overhead_s = max(t_short - steps_short * step_s, 0.0)
    return step_s, overhead_s


def mfu_fields(
    prefix: str, history: dict, peak: float | None
) -> dict[str, float]:
    """{prefix}_achieved_tflops / {prefix}_mfu_pct from a fit history.

    Achieved FLOP/s = the compiled program's XLA flop count over the
    measured train time; MFU = achieved / chip peak.  Empty when the
    trainer didn't record program_flops.
    """
    flops = history.get("program_flops")
    t = history.get("train_time_s")
    if not flops or not t:
        return {}
    achieved = flops / t
    out = {f"{prefix}_achieved_tflops": round(achieved / 1e12, 3)}
    if peak:
        out[f"{prefix}_mfu_pct"] = round(100.0 * achieved / peak, 2)
    return out
