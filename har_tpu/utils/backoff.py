"""Capped exponential backoff with deterministic seeded jitter — THE
retry-pacing policy shared by the fleet stack.

Two call sites need the same policy and must not drift:

  - the fleet engine's per-dispatch retry loop (``FleetServer``
    launch/retire re-attempts): the dispatch hot path never sleeps —
    retries are immediate — but the attempt counting and the give-up
    cap are this module's ``retry_call``;
  - the cluster control plane's router→worker heartbeat probes and
    hand-off retries (``har_tpu.serve.cluster``): the failure detector
    consumes ``next_ms()`` to SCHEDULE its next probe against the
    injected clock (no sleeping — the poll loop simply skips the
    worker until the delay has passed), and hand-off retries pass a
    clock-advancing ``sleep`` when the clock supports it — either way
    a flapping worker is retried at a decaying rate instead of
    hammered: the Spark-ML perf study's warning (arXiv 1612.01437)
    that coordination overhead dominates distributed ML, applied to
    our failure detector.

Determinism is a requirement, not a nicety (harlint HL004): the jitter
draw is seeded, so the same seed produces the same delay schedule and a
chaos run replays byte-identically.  ``reset()`` restarts BOTH the
exponent and the jitter stream — after a success the next failure sees
the exact schedule a fresh instance would.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Delay-schedule knobs: ``base_ms * factor**attempt`` capped at
    ``cap_ms``, plus a seeded uniform jitter of up to ``jitter`` times
    the un-jittered delay (the cap applies after jitter too — the cap
    is a promise, not a suggestion)."""

    base_ms: float = 50.0
    cap_ms: float = 2000.0
    factor: float = 2.0
    jitter: float = 0.25

    def __post_init__(self):
        if self.base_ms <= 0 or self.cap_ms < self.base_ms:
            raise ValueError("need 0 < base_ms <= cap_ms")
        if self.factor < 1.0 or not (0.0 <= self.jitter <= 1.0):
            raise ValueError("need factor >= 1 and jitter in [0, 1]")


class Backoff:
    """One retry sequence: ``next_ms()`` per failed attempt, ``reset()``
    on success.  Seeded: two instances with the same (policy, seed)
    produce the same delay sequence, and ``reset()`` restarts it."""

    def __init__(self, policy: BackoffPolicy | None = None, seed: int = 0):
        self.policy = policy or BackoffPolicy()
        self._seed = int(seed)
        self.attempt = 0
        self._rng = np.random.default_rng((self._seed, 0xB0FF))

    def next_ms(self) -> float:
        """Delay before the next attempt (milliseconds), advancing the
        schedule: base * factor^attempt + seeded jitter, capped."""
        p = self.policy
        raw = min(p.cap_ms, p.base_ms * p.factor**self.attempt)
        self.attempt += 1
        delay = raw + raw * p.jitter * float(self._rng.random())
        return min(p.cap_ms, delay)

    def reset(self) -> None:
        """Back to attempt 0 AND the start of the jitter stream — the
        schedule after a success is the schedule of a fresh instance.
        A no-op while already fresh: ``retry_call`` resets on every
        success, and the dispatch hot path must not pay a Generator
        rebuild per successfully launched batch."""
        if self.attempt == 0:
            return
        self.attempt = 0
        self._rng = np.random.default_rng((self._seed, 0xB0FF))


def retry_call(
    fn: Callable,
    *,
    retries: int,
    backoff: Backoff | None = None,
    sleep: Callable[[float], None] | None = None,
    on_retry: Callable[[int, Exception], None] | None = None,
):
    """Call ``fn()`` with up to ``retries`` transparent re-attempts.

    Returns ``fn()``'s value; re-raises the last exception once the
    budget is spent.  ``on_retry(attempt, exc)`` fires before each
    re-attempt (accounting hook — the fleet engine counts
    ``dispatch_retries`` here).  ``backoff.next_ms()`` is consumed per
    re-attempt and ``backoff.reset()`` runs on success; the wait itself
    happens only when ``sleep`` (seconds) is given — the fleet dispatch
    hot path passes ``sleep=None`` (it must never block; the schedule
    still advances so shared-backoff callers see the failures), while
    the cluster's hand-off retries pass the injected clock's
    ``advance`` so simulated time moves with each re-attempt.
    """
    if retries < 0:
        raise ValueError("retries must be >= 0")
    attempt = 0
    while True:
        try:
            out = fn()
        except Exception as exc:
            attempt += 1
            if attempt > retries:
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            if backoff is not None:
                delay_ms = backoff.next_ms()
                if sleep is not None:
                    sleep(delay_ms / 1e3)
        else:
            if backoff is not None:
                backoff.reset()
            return out
