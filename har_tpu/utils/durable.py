"""Durable filesystem writes — THE fsync discipline shared by the
model registry (har_tpu.adapt.registry) and the fleet journal
(har_tpu.serve.journal).

``os.replace`` alone only orders the rename against the file's own
data: after a crash the parent directory can still resurface the OLD
entry (or none) unless the directory itself is synced.  Every durable
pointer/log in this codebase goes through one of these three helpers
so the discipline cannot drift between subsystems.
"""

from __future__ import annotations

import os


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY entry table — the half of atomic-rename
    durability os.replace skips."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path: str, data: str) -> None:
    """tmp file → flush+fsync the DATA → rename over the target →
    fsync the PARENT DIRECTORY.  A reader sees the old content or the
    new content, and whichever it sees survives power loss."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))


def durable_append(path: str, line: str) -> None:
    """Append one line and fsync; the first append also syncs the
    parent directory (the file's dir entry is new)."""
    existed = os.path.exists(path)
    with open(path, "a") as f:
        f.write(line)
        f.flush()
        os.fsync(f.fileno())
    if not existed:
        fsync_dir(os.path.dirname(path))
