"""Tracing and step timing (SURVEY §5.1).

The reference's entire observability story is `time.time()` pairs around
each fit/transform printed into the report (reference Main/main.py:116-124
and five sibling blocks) — Spark's own UI/event-log is never configured.
This module is the TPU-native upgrade:

  - :func:`trace` — context manager around `jax.profiler.trace`, emitting
    a TensorBoard-loadable XLA trace (op-level HLO timing, HBM usage) to a
    directory; a no-op when disabled so call sites can leave it in place.
  - :class:`StepTimer` — wall-clock section timing with the reference's
    semantics (label → seconds, rounded like the report's "trained in N
    seconds" lines) plus windows/s derivation.
  - :func:`write_timing_csv` — persists timings next to the metric CSVs.

`jax.profiler` traces are the ground truth for *device* time; StepTimer
measures *host-observed* time (includes dispatch + transfer), which is what
the reference reports and what `bench.py`/`sweep` print — keep the two
distinct when comparing numbers.
"""

from __future__ import annotations

import contextlib
import csv
import os
import time


@contextlib.contextmanager
def trace(log_dir: str | None):
    """`with trace("/tmp/trace"):` profiles the block for TensorBoard.

    Pass None to disable (the context is then free), so pipelines can
    accept an optional ``--trace-dir`` and leave the call site unchanged.
    """
    if not log_dir:
        yield
        return
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Section:
    """One timed interval; ``.seconds`` is set when its block exits."""

    seconds: float = 0.0


class StepTimer:
    """Labelled wall-clock sections: ``with timer("lr_fit") as s: ...``.

    Repeated labels accumulate in the per-label totals (epochs, CV
    cells); the yielded :class:`Section` always holds just the interval
    its own block measured, so callers reporting a single fit don't pick
    up earlier runs under the same label.  ``rate(label, count)`` derives
    items/s the way the benchmark counts windows/s.
    """

    def __init__(self):
        self._totals: dict[str, float] = {}
        self._counts: dict[str, int] = {}

    @contextlib.contextmanager
    def __call__(self, label: str):
        section = Section()
        t0 = time.perf_counter()
        try:
            yield section
        finally:
            section.seconds = time.perf_counter() - t0
            self._totals[label] = (
                self._totals.get(label, 0.0) + section.seconds
            )
            self._counts[label] = self._counts.get(label, 0) + 1

    @property
    def seconds(self) -> dict[str, float]:
        return dict(self._totals)

    def calls(self, label: str) -> int:
        return self._counts.get(label, 0)

    def rate(self, label: str, items: int) -> float:
        total = self._totals.get(label, 0.0)
        return items / total if total > 0 else 0.0

    def rows(self) -> list[dict]:
        return [
            {
                "section": label,
                "seconds": round(total, 6),
                "calls": self._counts[label],
            }
            for label, total in self._totals.items()
        ]


def write_timing_csv(path: str, timer: StepTimer) -> str:
    """Persist section timings (the CSVs' sibling artifact, `timing.csv`)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", newline="") as f:
        writer = csv.DictWriter(
            f, fieldnames=["section", "seconds", "calls"]
        )
        writer.writeheader()
        writer.writerows(timer.rows())
    return path
