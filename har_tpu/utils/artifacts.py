"""One place that knows where measurement artifacts live.

The repo's perf evidence (hist_bench.json, cv_scaling.json,
long_context_bench.json, …) is written by scripts/ and read by bench.py
and library auto-policies; every reader resolving the path its own way
is how lookups drift apart.
"""

from __future__ import annotations

import json
import os

ARTIFACTS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))),
    "artifacts",
)


def artifact_path(name: str) -> str:
    return os.path.join(ARTIFACTS_DIR, name)


def load_artifact(name: str) -> dict | None:
    """Parsed artifact JSON, or None when absent/unreadable/corrupt."""
    try:
        with open(artifact_path(name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
