"""Structure-of-arrays session estate for the fleet engine.

PR 10 made the device side of a dispatch essentially free (fused
program, 8 B/window fetch, pooled slabs) and measured the Python host
plane as the next bottleneck at 1,000 sessions: every session owned its
own ring-buffer array, smoother arrays and Python counters, so each
delivery round and each retire paid thousands of scattered small-object
operations.  This module turns that dict-of-objects estate into
structure-of-arrays form — the ROADMAP "Host-plane scale: 10k–100k
sessions per worker" item:

  ``SessionArena`` — ONE contiguous block per kind of per-session
    state: ring buffers ``(capacity, window, channels)``, ring
    heads/fills (``n_seen`` / ``next_emit``) as int arrays, per-session
    accounting counters as int arrays, EMA smoother state as one
    ``(capacity, C)`` float64 block, and vote smoother state as an
    integer ring ``(capacity, vote_depth)``.  A session is a SLOT index
    into these arrays; admission allocates a slot, removal/hand-off
    recycles it (``release`` is O(1); the recycled row is reset at the
    next ``alloc``).  The batched ingest and retire paths then run ONE
    vectorized numpy operation over a whole delivery round or dispatch
    batch where the object estate ran thousands of Python statements.

  ``_ArenaAssembler`` / ``_SlotSmoother`` — the per-session façades.
    They subclass the SHARED ``_WindowAssembler`` / ``_Smoother``
    (har_tpu.serving — the same classes a standalone
    ``StreamingClassifier`` runs), redirecting storage into the arena
    through properties: the sequential code paths (odd chunk sizes,
    journal replay, snapshot/export/adopt) execute the parent classes'
    logic VERBATIM over arena-backed state, which is the bit-identity
    argument — there is no second implementation of window assembly or
    smoothing to drift.  The batched kernels below are the only new
    math, and each one is elementwise-identical to the sequential
    recurrence it replaces (EMA: the same ``a*p + (1-a)*e`` per
    element; vote: the same integer counts and the same
    newest-first tie-break; test-pinned at N=64 against independent
    classifiers across smoothing modes, chunkings, churn and ring
    depths 1–4).

  ``PendingArena`` — the queued-window estate in the same SoA form
    (PR 14; PR 11 deliberately left it per-object).  One completed,
    not-yet-scored window is a SLOT into parallel arrays — owning
    session's arena slot, ``t_index``, staging slot, enqueue clock,
    drift flag, ``dropped``/``launched`` bitmasks, a ``next_idx``
    link — plus the global FIFO as an index RING over those slots.
    Each session's pending view is the ``next_idx`` linked list hung
    off the session arena's ``pend_head``/``pend_tail`` columns, so
    enqueue, due-selection, batch assembly, shed-stalest walks,
    ``remove_session`` drop-flagging and retire unlinking are all
    array operations with zero per-window Python object allocation
    (test-pinned by an object-census test).  A slot is recycled when
    its two references — the ring-or-ticket one and the session-list
    one — are both released (``refs`` starts at 2; flagging a drop
    releases neither: flagged entries keep their queue position for
    the FIFO unlink, exactly like the per-object queue did).

What stays per-object, deliberately: drift monitors (their state is
per-session objects; their EWMA update is batched via
``DriftMonitor.update_many`` instead), the emitted ``StreamEvent``s
(they ARE the API), and the ``_FleetSession`` handle itself (a
slot-carrying façade whose counter attributes read through to the
arena).  Snapshots serialize slots BACK to the per-session layout
(``ring{i}`` / ``ema{i}`` arrays, per-session metadata dicts) and the
pending queue back to the stacked ``pending`` array in global FIFO
order, so the on-disk journal format is unchanged and pre-SoA
snapshots restore cleanly — test-pinned.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from har_tpu.serving import _Smoother, _WindowAssembler


class SessionArena:
    """Contiguous SoA storage for every per-session scalar and array.

    Grows geometrically (amortized — steady-state serving never
    reallocates).  Growth reallocates the blocks, which orphans any
    ring VIEW handed to an assembler — the engine re-points live
    assemblers when ``grows`` advances (``FleetServer._new_session``).
    """

    def __init__(
        self,
        window: int,
        channels: int,
        vote_depth: int = 5,
        capacity: int = 64,
    ):
        self.window = int(window)
        self.channels = int(channels)
        self.vote_depth = max(int(vote_depth), 1)
        capacity = max(int(capacity), 8)
        self.rings = np.zeros(
            (capacity, self.window, self.channels), np.float32
        )
        # ring heads/fills: samples absorbed, next emission boundary
        self.n_seen = np.zeros(capacity, np.int64)
        self.next_emit = np.zeros(capacity, np.int64)
        # per-session accounting (the _FleetSession façade reads these)
        self.raw_seen = np.zeros(capacity, np.int64)
        self.n_enqueued = np.zeros(capacity, np.int64)
        self.n_scored = np.zeros(capacity, np.int64)
        self.n_dropped = np.zeros(capacity, np.int64)
        self.n_live = np.zeros(capacity, np.int64)
        self.handoffs = np.zeros(capacity, np.int64)
        # vote smoother: integer ring of the last vote_depth raw labels
        self.votes = np.zeros((capacity, self.vote_depth), np.int64)
        self.vote_len = np.zeros(capacity, np.int64)
        self.vote_head = np.zeros(capacity, np.int64)
        # EMA smoother: allocated at the first EMA step (the class
        # count comes from the first scored probabilities); ema_set
        # marks slots whose row holds real state, ema_local marks
        # slots that fell back to façade-local storage (a width
        # mismatch after a swap to a model with a different C)
        self.ema: np.ndarray | None = None
        self.ema_set = np.zeros(capacity, bool)
        self.ema_local = np.zeros(capacity, bool)
        # per-session pending view (PendingArena): head/tail indices of
        # the session's next_idx linked list through the pending slots
        # (-1 = empty) — derived queue state, rebuilt by replay like
        # the queue itself, never serialized per session
        self.pend_head = np.full(capacity, -1, np.int64)
        self.pend_tail = np.full(capacity, -1, np.int64)
        self._free = list(range(capacity - 1, -1, -1))
        self.grows = 0

    # every per-slot block the arena owns — THE table state()/
    # load_state/_grow/alloc all read, so a field added to __init__
    # without joining it trips harlint HL002's state-completeness rule
    # (acceptance mutation pinned in tests/test_harlint.py; the slot
    # CONTENT itself is serialized per session by the engine snapshot,
    # which is what keeps the on-disk format pre-SoA-compatible)
    _SLOT_ARRAYS = (
        "rings", "n_seen", "next_emit", "raw_seen", "n_enqueued",
        "n_scored", "n_dropped", "n_live", "handoffs", "votes",
        "vote_len", "vote_head", "ema_set", "ema_local",
        "pend_head", "pend_tail",
    )

    @property
    def capacity(self) -> int:
        return len(self.rings)

    @property
    def in_use(self) -> int:
        return len(self.rings) - len(self._free)

    def _grow(self) -> None:
        cap = self.capacity
        new_cap = cap * 2
        for name in self._SLOT_ARRAYS:
            old = getattr(self, name)
            buf = np.zeros((new_cap,) + old.shape[1:], old.dtype)
            buf[:cap] = old
            setattr(self, name, buf)
        if self.ema is not None:
            buf = np.zeros((new_cap, self.ema.shape[1]), np.float64)
            buf[:cap] = self.ema
            self.ema = buf
        self._free.extend(range(new_cap - 1, cap - 1, -1))
        self.grows += 1

    def alloc(self) -> int:
        """Claim a slot with freshly reset state (recycled slots are
        scrubbed HERE, so ``release`` stays O(1) on the eviction path)."""
        if not self._free:
            self._grow()
        slot = self._free.pop()
        self.rings[slot].fill(0.0)
        self.n_seen[slot] = 0
        self.next_emit[slot] = self.window
        for name in (
            "raw_seen", "n_enqueued", "n_scored", "n_dropped", "n_live",
            "handoffs", "vote_len", "vote_head",
        ):
            getattr(self, name)[slot] = 0
        self.ema_set[slot] = False
        self.ema_local[slot] = False
        # fresh pending view: empty linked list (growth zero-fills the
        # slot arrays, and 0 is a VALID pending index — the scrub here
        # is what makes -1 the reliable empty sentinel)
        self.pend_head[slot] = -1
        self.pend_tail[slot] = -1
        return slot

    def release(self, slot: int) -> None:
        self._free.append(slot)

    # ------------------------------------------------- smoother blocks

    def ema_rows(self, width: int) -> np.ndarray | None:
        """The EMA block at the given class width — allocated on first
        use; None when an existing block has a DIFFERENT width (a swap
        to a model with another class count: those sessions fall back
        to façade-local state, flagged in ``ema_local``)."""
        if self.ema is None:
            self.ema = np.zeros((self.capacity, int(width)), np.float64)
        return self.ema if self.ema.shape[1] == int(width) else None

    def ema_block_for(self, alpha: float):
        """The batched EMA recurrence, bound to the engine's alpha:
        ``kernel(slots, probs)`` runs ``e' = a*p + (1-a)*e`` per
        element for initialized rows and ``e' = p`` for first-step
        rows over a block of DISTINCT slots — exactly the sequential
        ``_Smoother`` recurrence, one vectorized operation per case
        (elementwise, so bit-identical to per-session steps).  Returns
        the updated ``(m, C)`` block (a fresh gather), or None when
        the block cannot run vectorized (width mismatch /
        local-fallback rows) — the caller then steps the façades
        sequentially."""
        a = float(alpha)

        def kernel(slots: np.ndarray, probs: np.ndarray):
            if self.ema_local[slots].any():
                return None
            block = self.ema_rows(probs.shape[1])
            if block is None:
                return None
            initialized = self.ema_set[slots]
            if initialized.all():
                block[slots] = a * probs + (1.0 - a) * block[slots]
            else:
                fresh = slots[~initialized]
                block[fresh] = probs[~initialized]
                old = slots[initialized]
                if len(old):
                    block[old] = (
                        a * probs[initialized]
                        + (1.0 - a) * block[old]
                    )
                self.ema_set[slots] = True
            return block[slots]

        return kernel

    def vote_block(
        self, slots: np.ndarray, raws: np.ndarray, n_classes: int
    ) -> tuple[np.ndarray, np.ndarray] | None:
        """Batched majority-vote step for a block of DISTINCT slots:
        push each row's raw label into its integer vote ring, rebuild
        the counts, and decide with the same newest-first tie-break the
        sequential ``_Smoother`` uses.  Returns ``(labels, smoothed)``
        where ``smoothed`` is the trailing vote distribution per row —
        integer counts divided in float64, exactly the scalar math —
        or None when a stale vote exceeds the class width (a swap to a
        narrower model: the scalar path widens per session; those
        blocks fall back to façade steps)."""
        depth = self.vote_depth
        v, hd, ln = self.votes, self.vote_head[slots], self.vote_len[slots]
        # stale-wide check BEFORE any mutation: a vote from before a
        # swap to a narrower model needs the scalar path's per-session
        # count widening — returning None must leave the rings
        # untouched so the façade fallback is the FIRST push
        m = len(slots)
        ages = np.arange(depth)
        old_valid = ages[None, :] < ln[:, None]
        widest = int(raws.max()) if m else -1
        if old_valid.any():
            widest = max(widest, int(v[slots][old_valid].max()))
        if widest >= int(n_classes):
            return None  # stale wider vote: per-session widening path
        v[slots, hd] = raws
        hd2 = (hd + 1) % depth
        ln2 = np.minimum(ln + 1, depth)
        self.vote_head[slots] = hd2
        self.vote_len[slots] = ln2
        rows = v[slots]  # (m, depth) gather
        valid = ages[None, :] < ln2[:, None]  # (m, depth)
        # newest-first positions in the ring: age 0 = the vote just
        # pushed, age ln2-1 = the oldest surviving one
        pos = (hd2[:, None] - 1 - ages[None, :]) % depth  # (m, depth)
        votes_by_age = np.take_along_axis(rows, pos, axis=1)
        counts = np.zeros((m, int(n_classes)), np.int64)
        ridx = np.arange(m)
        for age in range(depth):
            live = valid[:, age]
            if not live.any():
                break
            np.add.at(counts, (ridx[live], votes_by_age[live, age]), 1)
        best = counts.max(axis=1)
        labels = np.full(m, -1, np.int64)
        for age in range(depth):
            undecided = labels < 0
            if not undecided.any():
                break
            cand = votes_by_age[:, age]
            pick = (
                undecided
                & valid[:, age]
                & (counts[ridx, cand] == best)
            )
            labels[pick] = cand[pick]
        smoothed = counts.astype(np.float64) / ln2[:, None]
        return labels, smoothed

    # ------------------------------------------------- observability

    @property
    def nbytes(self) -> int:
        """Resident bytes of every slot block (EMA included) — the
        ``arena_bytes`` footprint gauge's source (the 20k-session point
        of the scaling curve is partially memory-bound; this is the
        visibility the ROADMAP asked for)."""
        total = sum(
            int(getattr(self, name).nbytes) for name in self._SLOT_ARRAYS
        )
        if self.ema is not None:
            total += int(self.ema.nbytes)
        return total

    def state(self) -> dict:
        """Snapshot-provider payload: geometry + sizing observability,
        with one entry PER SLOT ARRAY (``_SLOT_ARRAYS``) — the
        per-session CONTENT itself is serialized back to the journal's
        per-session layout (``ring{i}``/``ema{i}`` arrays + metadata
        dicts) by the engine's snapshot builder, so the on-disk format
        is unchanged and pre-SoA snapshots restore cleanly.  Deleting a
        slot-array key from this serializer (the ``_SLOT_ARRAYS``
        table) fails the harlint HL002 gate — acceptance mutation
        pinned in tests/test_harlint.py."""
        return {
            "window": self.window,
            "channels": self.channels,
            "vote_depth": self.vote_depth,
            "capacity": self.capacity,
            "in_use": self.in_use,
            "grows": self.grows,
            "ema_width": (
                None if self.ema is None else int(self.ema.shape[1])
            ),
            "arrays": {
                name: int(getattr(self, name).nbytes)
                for name in self._SLOT_ARRAYS
            },
        }

    def load_state(self, state: dict) -> None:
        """Restore the geometry/observability gauges.  The slot arrays
        named in ``_SLOT_ARRAYS`` re-fill through the engine's
        per-session restore path (add_session + push/ack replay), and
        the EMA block re-derives its width at the first scored batch —
        what survives HERE is the construction geometry and the
        cumulative ``grows`` counter; ``capacity``/``in_use`` are live
        allocation properties recomputed by the restored engine's own
        admissions."""
        self.window = int(state.get("window", self.window))
        self.channels = int(state.get("channels", self.channels))
        self.vote_depth = int(state.get("vote_depth", self.vote_depth))
        self.grows = int(state.get("grows", 0))
        if state.get("ema_width") is None:
            self.ema = None
        unknown = [
            name
            for name in (state.get("arrays") or {})
            if name not in self._SLOT_ARRAYS
        ]
        if unknown:
            import warnings

            warnings.warn(
                "SessionArena.load_state: unknown slot arrays "
                f"{sorted(unknown)} — written by a newer version?",
                RuntimeWarning,
                stacklevel=2,
            )


class _ArenaAssembler(_WindowAssembler):
    """``_WindowAssembler`` whose ring and head/fill scalars live in a
    ``SessionArena`` slot.  ``consume`` (and every other parent method)
    runs VERBATIM: the ring is an arena row view, and the ``_n_seen`` /
    ``_next_emit`` scalars read/write the arena's int arrays through
    the properties below — so the sequential ingest path is the exact
    shared-code path, and only the engine's batched ``push_many`` fast
    path touches the arrays wholesale."""

    __slots__ = ("_arena", "_slot")

    def __init__(self, arena: SessionArena, slot: int, window, hop,
                 channels, monitor=None):
        self._arena = arena
        self._slot = slot
        super().__init__(
            window, hop, channels, monitor=monitor,
            ring=arena.rings[slot],
        )

    @property
    def _n_seen(self):
        return int(self._arena.n_seen[self._slot])

    @_n_seen.setter
    def _n_seen(self, value):
        self._arena.n_seen[self._slot] = value

    @property
    def _next_emit(self):
        return int(self._arena.next_emit[self._slot])

    @_next_emit.setter
    def _next_emit(self, value):
        self._arena.next_emit[self._slot] = value


class _SlotSmoother(_Smoother):
    """``_Smoother`` whose EMA/vote state lives in a ``SessionArena``
    slot.  The EMA recurrence is the parent's own ``_step_raw`` running
    through the ``_ema`` property (read: arena row or None; write:
    in-place row assignment — same float64 values).  The vote step
    round-trips the arena's integer ring through the parent's deque
    logic, so the decision code has exactly one implementation.  A
    width-mismatched EMA (model swap to a different class count) falls
    back to façade-local storage, flagged so the batched kernel skips
    those slots."""

    __slots__ = ("_arena", "_slot", "_ema_store")

    def __init__(self, arena: SessionArena, slot: int, smoothing,
                 ema_alpha, vote_depth):
        self._arena = arena
        self._slot = slot
        self._ema_store = None
        super().__init__(smoothing, ema_alpha, vote_depth)

    # ------------------------------------------------------ EMA state

    @property
    def _ema(self):
        if self._ema_store is not None:
            return self._ema_store
        a, s = self._arena, self._slot
        if a.ema is None or not a.ema_set[s]:
            return None
        return a.ema[s]

    @_ema.setter
    def _ema(self, value):
        a, s = self._arena, self._slot
        if value is None:
            self._ema_store = None
            a.ema_set[s] = False
            a.ema_local[s] = False
            return
        value = np.asarray(value, np.float64)
        rows = a.ema_rows(value.shape[0])
        if rows is None:
            # width mismatch with the allocated block: per-session
            # fallback (counted so the batched kernel skips the slot)
            self._ema_store = value
            a.ema_local[s] = True
            return
        rows[s] = value
        a.ema_set[s] = True
        a.ema_local[s] = False
        self._ema_store = None

    # ----------------------------------------------------- vote state

    @property
    def _votes(self):
        a, s = self._arena, self._slot
        depth = a.vote_depth
        ln = int(a.vote_len[s])
        hd = int(a.vote_head[s])
        d: deque[int] = deque(maxlen=depth)
        for i in range(ln):  # oldest → newest
            d.append(int(a.votes[s, (hd - ln + i) % depth]))
        return d

    @_votes.setter
    def _votes(self, value):
        a, s = self._arena, self._slot
        depth = a.vote_depth
        vals = [int(v) for v in value][-depth:]
        a.votes[s, : len(vals)] = vals
        a.vote_len[s] = len(vals)
        a.vote_head[s] = len(vals) % depth

    def _step_raw(self, raw_label, probs):
        if self.smoothing == "vote":
            # round-trip the arena ring through the PARENT's deque
            # logic: one decision implementation, arena-backed storage
            tmp = _Smoother(
                "vote", self.ema_alpha, self._arena.vote_depth
            )
            tmp._votes = self._votes
            out = _Smoother._step_raw(tmp, raw_label, probs)
            self._votes = tmp._votes
            return out
        out = super()._step_raw(raw_label, probs)
        if self.smoothing == "ema" and self._ema_store is None:
            # the parent returned the arena ROW (a live view): snapshot
            # it — the plain _Smoother allocates a fresh array per
            # step, so two windows of one session in one batch must
            # see two distinct EMA states, not the final one twice
            return (out[0], out[1], out[2].copy())
        return out


class PendingArena:
    """Slot-indexed SoA storage for the pending (queued-window) estate.

    One completed, not-yet-scored window is an index into parallel
    arrays; the global FIFO is an index RING over those slots.  A slot
    carries exactly what the per-object ``_Pending`` carried — owning
    session's arena slot, ``t_index``, staging slot, enqueue clock,
    drift flag, ``dropped``/``launched`` marks — plus the ``next_idx``
    link that threads each session's pending view (heads/tails live in
    the session arena's ``pend_head``/``pend_tail`` columns, engine-
    managed).

    Slot lifetime is reference-counted with exactly TWO references:
    the queue-side one (the FIFO ring until launch, then the dispatch
    ticket until retire — launch TRANSFERS it, so the count never
    moves on the hot path) and the session-list one (released at the
    retire unlink / lazy dropped-prefix discard / ``remove_session``
    clear).  Flagging a window ``dropped`` releases neither reference:
    flagged entries keep their position in both views, exactly the
    per-object queue's contract, and the slot recycles when the second
    reference goes (``release`` pushes it back on the free stack).

    Growth is geometric and amortized; steady-state serving allocates
    nothing per window — enqueue/pop/flag/release are all array writes
    (the zero-allocation contract is pinned by an object-census test).
    """

    def __init__(self, capacity: int = 256):
        capacity = max(int(capacity), 32)
        # per-slot columns — everything the per-object _Pending carried
        self.sess_slot = np.full(capacity, -1, np.int64)
        self.t_index = np.zeros(capacity, np.int64)
        self.stage_slot = np.zeros(capacity, np.int64)
        self.t_enqueue = np.zeros(capacity, np.float64)
        self.drift = np.zeros(capacity, bool)
        self.dropped = np.zeros(capacity, bool)
        self.launched = np.zeros(capacity, bool)
        self.next_idx = np.full(capacity, -1, np.int64)
        self.refs = np.zeros(capacity, np.uint8)
        self.grows = 0
        # free slots as an int stack (array + count): block allocation
        # is one slice, never a per-slot Python pop
        self._free = np.arange(capacity - 1, -1, -1, dtype=np.int64)
        self._n_free = capacity
        # the global FIFO: a power-of-two circular index ring with
        # monotonic head/tail counters.  Ring size is bounded by the
        # slot capacity (a ring entry holds a slot reference), so the
        # ring grows in step with the slot arrays.
        self._ring = np.empty(_pow2(capacity), np.int64)
        self._rhead = 0
        self._rtail = 0

    # every per-slot column the arena owns — THE table state()/
    # load_state read, so a field added to __init__ without joining it
    # trips harlint HL002's state-completeness rule (acceptance
    # mutation pinned in tests/test_harlint.py; the slot CONTENT
    # itself is serialized back to the snapshot's stacked ``pending``
    # array in global FIFO order by the engine, which is what keeps
    # the on-disk format pre-SoA-compatible)
    _PENDING_ARRAYS = (
        "sess_slot", "t_index", "stage_slot", "t_enqueue", "drift",
        "dropped", "launched", "next_idx", "refs",
    )

    @property
    def capacity(self) -> int:
        return len(self.sess_slot)

    @property
    def in_use(self) -> int:
        return len(self.sess_slot) - self._n_free

    @property
    def queued(self) -> int:
        """Entries currently in the FIFO ring (dropped-but-unpopped
        included) — the due-selection view's raw size."""
        return self._rtail - self._rhead

    @property
    def nbytes(self) -> int:
        """Resident bytes of the pending estate (ring + free stack
        included) — the ``pending_bytes`` footprint gauge's source."""
        return (
            sum(
                int(getattr(self, name).nbytes)
                for name in self._PENDING_ARRAYS
            )
            + int(self._ring.nbytes)
            + int(self._free.nbytes)
        )

    # ---------------------------------------------------- slot estate

    def _grow(self, need: int = 0) -> None:
        cap = self.capacity
        new_cap = cap * 2
        while new_cap < need:
            new_cap *= 2
        for name in self._PENDING_ARRAYS:
            old = getattr(self, name)
            buf = np.zeros(new_cap, old.dtype)
            buf[:cap] = old
            setattr(self, name, buf)
        free = np.empty(new_cap, np.int64)
        free[: self._n_free] = self._free[: self._n_free]
        free[self._n_free: self._n_free + new_cap - cap] = np.arange(
            new_cap - 1, cap - 1, -1
        )
        self._free = free
        self._n_free += new_cap - cap
        self.grows += 1

    def alloc_block(self, m: int) -> np.ndarray:
        """Claim ``m`` fresh slots (flags reset, both references held);
        FIFO position is the caller's job (``ring_extend``)."""
        if self._n_free < m:
            self._grow(self.in_use + m)
        idx = self._free[self._n_free - m: self._n_free].copy()
        self._n_free -= m
        self.dropped[idx] = False
        self.launched[idx] = False
        self.next_idx[idx] = -1
        self.refs[idx] = 2
        return idx

    def add_block(
        self, sess_slots, t_indices, stage_slots, drifts, now: float
    ) -> np.ndarray:
        """Enqueue a block of windows in one shot: claim slots, fill
        every column, append to the FIFO ring in block order.  The
        batched ingest's whole-round enqueue — a handful of array
        writes where the per-object queue ran five Python statements
        per window."""
        idx = self.alloc_block(len(sess_slots))
        self.sess_slot[idx] = sess_slots
        self.t_index[idx] = t_indices
        self.stage_slot[idx] = stage_slots
        self.drift[idx] = drifts
        self.t_enqueue[idx] = now
        self.ring_extend(idx)
        return idx

    def add(
        self, sess_slot: int, t_index: int, stage_slot, drift: bool,
        now: float,
    ) -> int:
        """Scalar enqueue (the sequential ``push`` / replay path)."""
        if not self._n_free:
            self._grow()
        self._n_free -= 1
        i = self._free[self._n_free]
        self.sess_slot[i] = sess_slot
        self.t_index[i] = t_index
        self.stage_slot[i] = stage_slot
        self.t_enqueue[i] = now
        self.drift[i] = drift
        self.dropped[i] = False
        self.launched[i] = False
        self.next_idx[i] = -1
        self.refs[i] = 2
        self._ring_append(i)
        return int(i)

    def release(self, i: int) -> None:
        """Drop one reference; recycle the slot when both are gone."""
        self.refs[i] -= 1
        if not self.refs[i]:
            if self._n_free >= len(self._free):  # pragma: no cover
                raise AssertionError("pending free-stack overflow")
            self._free[self._n_free] = i
            self._n_free += 1

    def release_block(self, idx: np.ndarray) -> None:
        """Vectorized reference drop (the end-of-retire ticket
        release): one subtract, one mask, one slice write."""
        if not len(idx):
            return
        self.refs[idx] -= 1
        done = idx[self.refs[idx] == 0]
        m = len(done)
        if m:
            self._free[self._n_free: self._n_free + m] = done
            self._n_free += m

    # ------------------------------------------------ the FIFO ring

    def _ring_grow(self) -> None:
        cap = len(self._ring)
        size = self._rtail - self._rhead
        buf = np.empty(cap * 2, np.int64)
        h = self._rhead & (cap - 1)
        first = min(cap - h, size)
        buf[:first] = self._ring[h: h + first]
        buf[first:size] = self._ring[: size - first]
        self._ring = buf
        self._rhead = 0
        self._rtail = size

    def _ring_append(self, i: int) -> None:
        if self._rtail - self._rhead >= len(self._ring):
            self._ring_grow()
        self._ring[self._rtail & (len(self._ring) - 1)] = i
        self._rtail += 1

    def ring_extend(self, idx: np.ndarray) -> None:
        m = len(idx)
        while self._rtail - self._rhead + m > len(self._ring):
            self._ring_grow()
        cap = len(self._ring)
        t = self._rtail & (cap - 1)
        first = min(cap - t, m)
        self._ring[t: t + first] = idx[:first]
        if first < m:
            self._ring[: m - first] = idx[first:]
        self._rtail += m

    def ring_indices(self) -> np.ndarray:
        """The FIFO ring's contents in queue order (dropped-but-
        unpopped entries included) — the snapshot serializer's and the
        shed-stalest walk's view.  A fresh array, never a live view."""
        cap = len(self._ring)
        size = self._rtail - self._rhead
        h = self._rhead & (cap - 1)
        first = min(cap - h, size)
        out = np.empty(size, np.int64)
        out[:first] = self._ring[h: h + first]
        out[first:] = self._ring[: size - first]
        return out

    def pop_batch(self, target: int) -> np.ndarray:
        """Pop up to ``target`` LIVE entries off the FIFO head in one
        vectorized sweep per contiguous ring segment, marking them
        launched; dropped entries encountered on the way are popped
        and their queue-side reference released (their session-list
        reference — and their flagged position there — is untouched,
        exactly like the per-object pop-and-skip).  Returns the
        launched indices in FIFO order."""
        taken: list[np.ndarray] = []
        got = 0
        cap = len(self._ring)
        while got < target and self._rtail > self._rhead:
            h = self._rhead & (cap - 1)
            seg = min(
                cap - h, self._rtail - self._rhead, target - got
            )
            chunk = self._ring[h: h + seg].copy()
            mask = self.dropped[chunk]
            if mask.any():
                dead = chunk[mask]
                self.release_block(dead)
                chunk = chunk[~mask]
            self._rhead += seg
            if len(chunk):
                self.launched[chunk] = True
                taken.append(chunk)
                got += len(chunk)
        if not taken:
            return _EMPTY_IDX
        return taken[0] if len(taken) == 1 else np.concatenate(taken)

    def head_live(self, n: int) -> np.ndarray:
        """The first ``n`` LIVE indices from the FIFO head, in queue
        order, WITHOUT popping anything — the shed-stalest walk's
        view.  Stops as soon as ``n`` are found (one vectorized mask
        per ring segment), so shedding one window off a deep queue is
        O(shed + dropped prefix), not O(queue)."""
        found: list[np.ndarray] = []
        got = 0
        cap = len(self._ring)
        pos = self._rhead
        while got < n and pos < self._rtail:
            h = pos & (cap - 1)
            seg = min(cap - h, self._rtail - pos)
            chunk = self._ring[h: h + seg]
            live = chunk[~self.dropped[chunk]]
            if len(live):
                found.append(live[: n - got])
                got += len(found[-1])
            pos += seg
        if not found:
            return _EMPTY_IDX
        return found[0] if len(found) == 1 else np.concatenate(found)

    def oldest_live_enqueue(self) -> float | None:
        """Enqueue clock of the FIFO head's oldest live entry (the
        micro-batcher's deadline input), popping-and-releasing dropped
        heads on the way — the per-object ``_oldest_live`` as array
        ops."""
        cap = len(self._ring)
        while self._rtail > self._rhead:
            h = self._rhead & (cap - 1)
            seg = min(cap - h, self._rtail - self._rhead)
            chunk = self._ring[h: h + seg]
            live = np.flatnonzero(~self.dropped[chunk])
            if len(live):
                n_dead = int(live[0])
                if n_dead:
                    self.release_block(chunk[:n_dead].copy())
                    self._rhead += n_dead
                return float(self.t_enqueue[chunk[n_dead]])
            self.release_block(chunk.copy())
            self._rhead += seg
        return None

    # ------------------------------------------------- observability

    def state(self) -> dict:
        """Snapshot-provider payload: sizing observability only, one
        entry PER PENDING ARRAY (``_PENDING_ARRAYS``) — the queued
        windows themselves serialize back to the snapshot's stacked
        ``pending`` array in global FIFO order (engine snapshot
        builder), so the on-disk format is unchanged and pre-SoA
        snapshots restore cleanly.  Deleting a column key from this
        serializer (the ``_PENDING_ARRAYS`` table) fails the harlint
        HL002 gate — acceptance mutation pinned in
        tests/test_harlint.py."""
        return {
            "capacity": self.capacity,
            "in_use": self.in_use,
            "queued": self.queued,
            "grows": self.grows,
            "nbytes": self.nbytes,
            "arrays": {
                name: int(getattr(self, name).nbytes)
                for name in self._PENDING_ARRAYS
            },
        }

    def load_state(self, state: dict) -> None:
        """Restore the observability gauges.  The columns named in
        ``_PENDING_ARRAYS`` re-fill through the engine's pending-queue
        restore path (snapshot ``pending`` rows + push/ack replay);
        what survives HERE is the cumulative ``grows`` counter —
        ``capacity``/``in_use``/``queued`` are live allocation
        properties recomputed by the restored queue itself."""
        self.grows = int(state.get("grows", 0))
        unknown = [
            name
            for name in (state.get("arrays") or {})
            if name not in self._PENDING_ARRAYS
        ]
        if unknown:
            import warnings

            warnings.warn(
                "PendingArena.load_state: unknown pending arrays "
                f"{sorted(unknown)} — written by a newer version?",
                RuntimeWarning,
                stacklevel=2,
            )


def _pow2(n: int) -> int:
    return 1 << (max(int(n), 2) - 1).bit_length()


_EMPTY_IDX = np.empty(0, np.int64)
