"""Consistent-hash session router: which worker owns which session.

Partitioning the fleet by ``hash(session_id)`` alone would reshuffle
nearly every session whenever a worker joins or dies — a full-fleet
migration storm for a one-worker event.  The classic fix is a
consistent-hash ring with virtual nodes: each worker owns many small
arcs of the hash circle, a session maps to the first worker clockwise
of its own hash, and removing a worker reassigns ONLY that worker's
arcs (about 1/N of the sessions) to the survivors.

The hash is ``blake2b`` over the stringified key — deterministic across
processes and runs (no process-seeded ``hash()``, harlint HL004), so
every router replica computes the same ownership table from the same
membership.

The ring decides PLACEMENT (where a new session is admitted, where a
dead worker's sessions fail over to); the controller keeps the live
``session → worker`` map on top of it, because a migrated session stays
pinned to its adopter even if the ring would hash it elsewhere — see
``har_tpu.serve.cluster.controller``.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Hashable


def stable_hash(key: Hashable) -> int:
    """64-bit deterministic hash of a session/worker key (blake2b —
    stable across processes, unlike Python's seeded ``hash``)."""
    digest = hashlib.blake2b(
        repr(key).encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


class ConsistentHashRouter:
    """Virtual-node consistent-hash ring over worker ids."""

    def __init__(self, replicas: int = 64):
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        self.replicas = int(replicas)
        self._points: list[int] = []  # sorted ring positions
        self._owners: list[str] = []  # worker id per ring position
        self._workers: list = []

    @property
    def workers(self) -> tuple:
        return tuple(self._workers)

    def add_worker(self, worker_id) -> None:
        if worker_id in self._workers:
            raise ValueError(f"worker {worker_id!r} already on the ring")
        for r in range(self.replicas):
            point = stable_hash((worker_id, r))
            i = bisect.bisect_left(self._points, point)
            self._points.insert(i, point)
            self._owners.insert(i, worker_id)
        self._workers.append(worker_id)

    def remove_worker(self, worker_id) -> None:
        if worker_id not in self._workers:
            raise ValueError(f"worker {worker_id!r} not on the ring")
        keep = [
            (p, w)
            for p, w in zip(self._points, self._owners)
            if w != worker_id
        ]
        self._points = [p for p, _ in keep]
        self._owners = [w for _, w in keep]
        self._workers.remove(worker_id)

    def owner(self, session_id: Hashable):
        """The worker whose arc covers this session's hash: first ring
        point clockwise (wrapping) of ``stable_hash(session_id)``."""
        if not self._points:
            raise ValueError("no workers on the ring")
        i = bisect.bisect_right(self._points, stable_hash(session_id))
        return self._owners[i % len(self._points)]

    def partition(self, session_ids) -> dict:
        """``{worker_id: [session_ids...]}`` for a batch of sessions —
        every live worker appears, even with an empty share."""
        out = {w: [] for w in self._workers}
        for sid in session_ids:
            out[self.owner(sid)].append(sid)
        return out
