"""DrJAX-style MapReduce primitives over cluster workers.

DrJAX (arXiv 2403.07128, PAPERS.md) expresses federated/parallel-
across-clients computation as three building blocks — ``broadcast`` a
value to every client, ``map_fn`` a function over clients, ``reduce``
the per-client results — and lowers them onto JAX sharding so the same
program runs on one host or a mesh.  The cluster controller speaks the
same algebra over WORKERS: fleet-stats aggregation is a map+reduce,
drift-evidence collection is a map, config pushes are a broadcast.

This module is the host-side reference lowering (plain Python over the
in-process worker list — the control plane runs at heartbeat cadence,
thousands of times below the dispatch rate, so a device lowering would
be measurement noise here).  Keeping the controller's aggregation
BEHIND these three names is the point: a future multi-host transport
(or an actual DrJAX lowering for million-session fleets) replaces this
module, not the controller.

``reduce_sum`` is numpy-aware and dict-recursive so a list of
``FleetStats.accounting()`` dicts reduces key-wise in one call —
that is the cross-worker conservation law's summation.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np


def broadcast(value, workers: Sequence) -> list:
    """One value, every worker — the controller→worker config/model
    push shape.  Returns the per-worker list ``map_fn`` consumes."""
    return [value for _ in workers]


def map_fn(fn: Callable, workers: Sequence) -> list:
    """Apply ``fn`` to every worker, in membership order (the order is
    part of the contract: zip-able with the worker list)."""
    return [fn(w) for w in workers]


def reduce_sum(values: Sequence):
    """Key-wise / element-wise sum of per-worker results.

    Dicts reduce recursively over the UNION of keys (a worker that has
    never failed over simply contributes 0 to ``worker_failovers``);
    numbers and arrays sum directly; booleans AND (so reducing
    ``accounting()`` dicts keeps ``balanced`` honest: the global law
    holds only if every worker's does AND the sums agree — the caller
    re-derives the global balance from the summed fields)."""
    values = list(values)
    if not values:
        return 0
    head = values[0]
    if isinstance(head, dict):
        keys: list = []
        for v in values:
            for k in v:
                if k not in keys:
                    keys.append(k)
        return {
            k: reduce_sum([v[k] for v in values if k in v]) for k in keys
        }
    if isinstance(head, bool):
        return all(values)
    if isinstance(head, np.ndarray):
        return np.sum(np.stack(values), axis=0)
    return sum(values)


def reduce_mean(values: Sequence):
    """Mean over workers (scalar/array leaves only)."""
    values = list(values)
    if not values:
        return 0.0
    if isinstance(values[0], np.ndarray):
        return np.mean(np.stack(values), axis=0)
    return sum(values) / len(values)
