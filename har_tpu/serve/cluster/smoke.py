"""The release gate's cluster-failover check.

Three workers, a real load (FakeClock + DispatchFaults, a mid-run hot
swap in the schedule), one worker SIGKILLed mid-dispatch — the cluster
must detect the death through the lease protocol, fail the partition
over via journal hand-off, and end with global conservation intact,
zero double-scored events and every migrated stream bit-identical to
the un-killed run.  One `mid_dispatch` cell of the full worker-axis
chaos matrix (tests/test_cluster.py runs all of it); the gate stamps
``{workers, failovers, migrated_sessions, windows_lost, migration_ms}``
into artifacts/test_gate.json.
"""

from __future__ import annotations

from har_tpu.serve.chaos import run_cluster_kill_point


def cluster_failover_smoke(
    sessions: int = 24, workers: int = 3, seed: int = 0
) -> dict:
    """Gate verdict: run the ``mid_dispatch`` worker-kill cell and
    reshape its evidence into the gate-log stamp."""
    out = run_cluster_kill_point(
        "mid_dispatch", sessions=sessions, workers=workers, seed=seed
    )
    return {
        "ok": bool(out["ok"]),
        "why": out["why"],
        "sessions": int(sessions),
        "workers": out.get("workers"),
        "failovers": out.get("failovers"),
        "migrated_sessions": out.get("migrated_sessions"),
        "windows_lost": out.get("windows_lost"),
        "migration_ms": out.get("migration_ms"),
    }


def failover_benchmark(
    session_counts,
    n_runs: int = 3,
    *,
    workers: int = 3,
    seed: int = 0,
    n_samples: int = 300,
) -> list[dict]:
    """THE failover-latency measurement behind bench.py's
    ``cluster_failover`` lane: per fleet size, drive an N-worker
    cluster under FakeClock load, SIGKILL one worker once windows are
    flowing, and let the control plane do its job — the row reports
    the failover wall time (restore + drain + hand-offs,
    ``FleetCluster.failover_ms``), the receiver-side migration time,
    and ``contract_ok`` pinning the global conservation law + complete
    delivery on every measured run."""
    import shutil
    import tempfile

    import numpy as np

    from har_tpu.serve.chaos import (
        _build_cluster,
        _drive_cluster,
        _recordings,
    )
    from har_tpu.serve.faults import FakeClock
    from har_tpu.serve.loadgen import AnalyticDemoModel

    model = AnalyticDemoModel()
    rows = []
    for n_sessions in session_counts:
        recordings = _recordings(int(n_sessions), n_samples, 3, seed)
        times, mig_ms, migrated, ok = [], [], 0, True
        for _ in range(int(n_runs)):
            root = tempfile.mkdtemp(prefix="har_cluster_bench_")
            try:
                clock = FakeClock()
                cluster = _build_cluster(
                    root, clock, sessions=int(n_sessions),
                    workers=workers, window=100, hop=50, model=model,
                    flush_every=512, snapshot_every=0,
                    loader=lambda ver: model,
                )
                for i in range(int(n_sessions)):
                    cluster.add_session(i)
                victim = cluster.worker_of(0)
                killed = {"done": False}

                def on_round(c):
                    if (
                        not killed["done"]
                        and c.accounting()["scored"] > 0
                    ):
                        c._workers[victim].kill()
                        killed["done"] = True

                events: list = []
                _drive_cluster(
                    cluster, recordings, [0] * int(n_sessions),
                    n_samples, 50, clock, events, on_round,
                )
                stats = cluster.cluster_stats()
                acct = stats["accounting"]
                times.append(stats["failover_ms"])
                mig_ms.append(stats["migration_ms"])
                migrated = stats["migrated_sessions"]
                keys = {(e.session_id, e.event.t_index) for e in events}
                ok = ok and (
                    acct["balanced"]
                    and acct["pending"] == 0
                    and stats["failovers"] == 1
                    and len(keys) == len(events)  # zero double-scored
                )
                cluster.close()
            finally:
                shutil.rmtree(root, ignore_errors=True)
        rows.append(
            {
                "n_sessions": int(n_sessions),
                "workers": int(workers),
                "migrated_sessions": int(migrated),
                "failover_ms_median": round(float(np.median(times)), 3),
                "failover_ms_std": round(float(np.std(times)), 3),
                "migration_ms_median": round(
                    float(np.median(mig_ms)), 3
                ),
                "contract_ok": ok,
            }
        )
    return rows


if __name__ == "__main__":
    import json

    print(json.dumps(cluster_failover_smoke()))
