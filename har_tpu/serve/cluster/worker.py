"""One cluster worker: an unmodified FleetServer + its PR-4 journal,
behind a process-boundary shim.

The worker wrapper is deliberately thin — the whole point of the
cluster design is that a worker is the SAME crash-safe engine the
single-process fleet runs (``FleetServer`` + ``FleetJournal``), so
every per-worker guarantee (conservation law, ack boundary, chaos
matrix) carries over verbatim.  What the wrapper adds is the failure
surface a real process boundary has: once a worker is killed, every
call raises ``WorkerUnavailable`` instead of touching dead state —
which is exactly the evidence the membership layer's failure detector
consumes.

``kill()`` is the in-process SIGKILL model, same stance as the chaos
harness: process memory is gone (the wrapper refuses all further
calls) and the journal drops its un-flushed buffer
(``FleetJournal.kill``) — what is on disk afterwards is exactly what a
real kill would have left.
"""

from __future__ import annotations

from typing import Hashable

from har_tpu.serve.cluster.membership import WorkerUnavailable


class ClusterWorker:
    """A FleetServer bound to a worker id and a journal directory."""

    def __init__(self, worker_id, server, journal_dir: str):
        self.worker_id = worker_id
        self.server = server
        self.journal_dir = journal_dir
        self.alive = True

    def _guard(self) -> None:
        if not self.alive:
            raise WorkerUnavailable(
                f"worker {self.worker_id!r} is not responding"
            )

    # ----------------------------------------------------- the "RPCs"

    def heartbeat(self) -> bool:
        """The membership probe: cheap, no fleet state touched."""
        self._guard()
        return True

    def push(self, session_id: Hashable, samples) -> int:
        self._guard()
        return self.server.push(session_id, samples)

    def push_many(self, session_ids, chunks) -> int:
        """Batched multi-session delivery in delivery order —
        semantically a sequence of ``push`` calls
        (``FleetServer.push_many``'s contract), one call instead of N.
        Over the wire this is what collapses a round's N push RPCs
        into one frame."""
        self._guard()
        return self.server.push_many(session_ids, chunks)

    def poll(self, *, force: bool = False) -> list:
        self._guard()
        return self.server.poll(force=force)

    def add_session(self, session_id: Hashable, *, monitor=None) -> None:
        self._guard()
        self.server.add_session(session_id, monitor=monitor)

    def disconnect_session(self, session_id: Hashable) -> list:
        """Graceful churn disconnect: partial-window flush + settle +
        journaled eviction (``FleetServer.disconnect_session``); the
        settle's events are returned to the caller."""
        self._guard()
        return self.server.disconnect_session(session_id)

    def disconnect_sessions(self, session_ids) -> list:
        """Batched graceful disconnect — one settle for the whole
        cohort leaving this worker (``FleetServer.disconnect_sessions``)."""
        self._guard()
        return self.server.disconnect_sessions(session_ids)

    def adopt(self, export: dict) -> None:
        """Adopt a migrated session and make the adopt record durable
        before returning — the target-side half of the hand-off
        protocol's adopt-first ordering.  Idempotent: a retry after a
        failed flush skips the admit and completes the durability."""
        self._guard()
        if export["sid"] not in self.server._sessions:
            self.server.adopt_session(export)
        if self.server.journal is not None:
            self.server.journal.flush()

    def owns(self, session_id: Hashable) -> bool:
        return self.alive and session_id in self.server._sessions

    def watermark(self, session_id: Hashable) -> int:
        self._guard()
        return self.server.watermark(session_id)

    # ------------------------------------------- control-plane surface
    # (PR 13: the controller speaks ONLY this surface — never
    # ``worker.server.<attr>`` — so the transport-backed twin
    # (har_tpu.serve.net.NetWorker) can implement the same methods as
    # RPCs and the controller stays transport-blind.)

    def export_session(self, session_id: Hashable) -> dict:
        self._guard()
        return self.server.export_session(session_id)

    def evict_session(self, session_id: Hashable) -> None:
        """Source half of a hand-off: journaled eviction + flush (the
        record must be durable before the controller moves on)."""
        self._guard()
        self.server.handoff_session(session_id)
        if self.server.journal is not None:
            self.server.journal.flush()

    def sessions(self) -> tuple:
        return tuple(self.server.sessions)

    def session_count(self) -> int:
        return len(self.server._sessions)

    def generation(self, session_id: Hashable) -> int:
        """The session's ``handoffs`` generation — the dual-ownership
        tie-break a takeover controller sorts by."""
        return int(self.server._sessions[session_id].handoffs)

    def undrained(self) -> list:
        """Sessions with live (queued or in-flight) windows — what a
        planned retire must refuse on."""
        return [
            sid
            for sid in self.server.sessions
            if self.server._sessions[sid].n_live
        ]

    def model_version(self) -> str:
        self._guard()
        return self.server.model_version

    def swap_model(self, model, *, version: str) -> None:
        self._guard()
        if self.server.model_version != version:
            self.server.swap_model(model, version=version)

    def geometry(self) -> dict:
        s = self.server
        return {
            "window": s.window,
            "hop": s.hop,
            "channels": s.channels,
            "smoothing": s.smoothing,
            "target_batch": int(s.config.target_batch),
            "pipeline_depth": int(s.config.pipeline_depth),
        }

    def accounting(self) -> dict:
        return self.server.stats.accounting()

    def final_accounting(self) -> dict:
        """The ledger entry a planned retire commits."""
        return {
            "accounting": self.server.stats.accounting(),
            "scored_by_version": dict(self.server.stats.scored_by_version),
        }

    def control_stats(self) -> dict:
        s = self.server.stats
        return {
            "worker_failovers": s.worker_failovers,
            "migrations": s.migrations,
            "migration_ms": s.migration_ms,
            "sessions": len(self.server._sessions),
        }

    def drift_reports(self) -> list:
        """Every monitored session's latest ``DriftReport`` as
        ``[(sid, report)]`` (monitor-less sessions skipped) — the
        evidence the fleet-global retrain trigger aggregates.  Part of
        the worker surface so the transport twin can ship it
        (``NetWorker.drift_reports`` rides the float64-exact wire
        codec) and ``NetCluster.observe_drift`` stops being refused."""
        self._guard()
        out = []
        for sid in self.server.sessions:
            report = self.server.drift_report(sid)
            if report is not None:
                out.append((sid, report))
        return out

    def note_failover_absorbed(self) -> None:
        self._guard()
        self.server.stats.worker_failovers += 1

    def note_migration_ms(self, ms: float) -> None:
        self._guard()
        self.server.stats.migration_ms += float(ms)

    # ----------------------------------------------------- lifecycle

    def kill(self) -> None:
        """SIGKILL model: refuse all further calls, drop the journal's
        un-flushed buffer.  Idempotent."""
        self.alive = False
        if self.server.journal is not None:
            self.server.journal.kill()

    def close(self) -> None:
        if self.alive and self.server.journal is not None:
            self.server.journal.close()
        self.alive = False
