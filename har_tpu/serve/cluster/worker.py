"""One cluster worker: an unmodified FleetServer + its PR-4 journal,
behind a process-boundary shim.

The worker wrapper is deliberately thin — the whole point of the
cluster design is that a worker is the SAME crash-safe engine the
single-process fleet runs (``FleetServer`` + ``FleetJournal``), so
every per-worker guarantee (conservation law, ack boundary, chaos
matrix) carries over verbatim.  What the wrapper adds is the failure
surface a real process boundary has: once a worker is killed, every
call raises ``WorkerUnavailable`` instead of touching dead state —
which is exactly the evidence the membership layer's failure detector
consumes.

``kill()`` is the in-process SIGKILL model, same stance as the chaos
harness: process memory is gone (the wrapper refuses all further
calls) and the journal drops its un-flushed buffer
(``FleetJournal.kill``) — what is on disk afterwards is exactly what a
real kill would have left.
"""

from __future__ import annotations

from typing import Hashable

from har_tpu.serve.cluster.membership import WorkerUnavailable


class ClusterWorker:
    """A FleetServer bound to a worker id and a journal directory."""

    def __init__(self, worker_id, server, journal_dir: str):
        self.worker_id = worker_id
        self.server = server
        self.journal_dir = journal_dir
        self.alive = True

    def _guard(self) -> None:
        if not self.alive:
            raise WorkerUnavailable(
                f"worker {self.worker_id!r} is not responding"
            )

    # ----------------------------------------------------- the "RPCs"

    def heartbeat(self) -> bool:
        """The membership probe: cheap, no fleet state touched."""
        self._guard()
        return True

    def push(self, session_id: Hashable, samples) -> int:
        self._guard()
        return self.server.push(session_id, samples)

    def poll(self, *, force: bool = False) -> list:
        self._guard()
        return self.server.poll(force=force)

    def add_session(self, session_id: Hashable, *, monitor=None) -> None:
        self._guard()
        self.server.add_session(session_id, monitor=monitor)

    def disconnect_session(self, session_id: Hashable) -> list:
        """Graceful churn disconnect: partial-window flush + settle +
        journaled eviction (``FleetServer.disconnect_session``); the
        settle's events are returned to the caller."""
        self._guard()
        return self.server.disconnect_session(session_id)

    def disconnect_sessions(self, session_ids) -> list:
        """Batched graceful disconnect — one settle for the whole
        cohort leaving this worker (``FleetServer.disconnect_sessions``)."""
        self._guard()
        return self.server.disconnect_sessions(session_ids)

    def adopt(self, export: dict) -> None:
        """Adopt a migrated session and make the adopt record durable
        before returning — the target-side half of the hand-off
        protocol's adopt-first ordering.  Idempotent: a retry after a
        failed flush skips the admit and completes the durability."""
        self._guard()
        if export["sid"] not in self.server._sessions:
            self.server.adopt_session(export)
        if self.server.journal is not None:
            self.server.journal.flush()

    def owns(self, session_id: Hashable) -> bool:
        return self.alive and session_id in self.server._sessions

    def watermark(self, session_id: Hashable) -> int:
        self._guard()
        return self.server.watermark(session_id)

    # ----------------------------------------------------- lifecycle

    def kill(self) -> None:
        """SIGKILL model: refuse all further calls, drop the journal's
        un-flushed buffer.  Idempotent."""
        self.alive = False
        if self.server.journal is not None:
            self.server.journal.kill()

    def close(self) -> None:
        if self.alive and self.server.journal is not None:
            self.server.journal.close()
        self.alive = False
