"""Multi-worker fleet control plane: consistent-hash routing, heartbeat
failover, journal hand-off session migration, cross-worker conservation.

Public surface:
  FleetCluster / ClusterConfig / ClusterError — the controller
  ConsistentHashRouter / stable_hash         — session partitioning
  Membership / LeaseConfig / WorkerUnavailable — failure detection
  ClusterWorker                              — one FleetServer worker
  broadcast / map_fn / reduce_sum / reduce_mean — DrJAX-style
                                               aggregation primitives
  cluster_failover_smoke                     — the release gate's check

See docs/multihost.md for the lease protocol, the hand-off sequence and
the cross-worker conservation law.
"""

from har_tpu.serve.cluster.controller import (
    RETIRED_MARKER,
    ClusterConfig,
    ClusterError,
    FleetCluster,
)
from har_tpu.serve.cluster.membership import (
    LeaseConfig,
    Membership,
    WorkerTimeout,
    WorkerUnavailable,
)
from har_tpu.serve.cluster.primitives import (
    broadcast,
    map_fn,
    reduce_mean,
    reduce_sum,
)
from har_tpu.serve.cluster.router import ConsistentHashRouter, stable_hash
from har_tpu.serve.cluster.smoke import cluster_failover_smoke
from har_tpu.serve.cluster.worker import ClusterWorker

__all__ = [
    "RETIRED_MARKER",
    "ClusterConfig",
    "ClusterError",
    "ClusterWorker",
    "ConsistentHashRouter",
    "FleetCluster",
    "LeaseConfig",
    "Membership",
    "WorkerTimeout",
    "WorkerUnavailable",
    "broadcast",
    "cluster_failover_smoke",
    "map_fn",
    "reduce_mean",
    "reduce_sum",
    "stable_hash",
]
