"""Heartbeat/lease failure detection for cluster workers.

A worker is alive while it keeps renewing its lease; a worker that
stops answering is SUSPECTED, probed at a capped-exponential-backoff
cadence (``har_tpu.utils.backoff`` — the same policy the dispatch
retry loop uses), and declared DEAD only when BOTH hold:

  - its lease expired (``lease_s`` without a successful heartbeat), and
  - ``probe_retries`` consecutive probes failed.

The two-condition rule is deliberate: a lease alone declares death on
one slow poll; probes alone declare it on a transient burst of refused
connections.  Requiring both bounds the false-positive rate (a false
death triggers a full partition migration — expensive to be wrong
about) while the backoff bounds the probe traffic (the Spark-ML perf
study's point that coordination overhead, not compute, dominates
distributed ML: a dead worker must not be hammered at line rate).

No wall clocks (harlint HL004): every deadline reads the injected
clock, so the whole failure detector runs deterministically under a
``FakeClock`` in the chaos harness.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

from har_tpu.utils.backoff import Backoff, BackoffPolicy


class WorkerUnavailable(RuntimeError):
    """A routed call reached a dead or unreachable worker."""


class WorkerTimeout(WorkerUnavailable):
    """The worker did not answer inside the call deadline — a SLOW LINK
    or a busy worker, not death evidence.  Subclasses WorkerUnavailable
    so every existing "worker did not serve this call" path still
    catches it; the failure detector routes it to ``note_timeout``
    (re-paced probe, NO strike) instead of ``note_failure`` — a
    congested-but-alive worker must never be failovered spuriously
    (test-pinned both paths)."""


@dataclasses.dataclass(frozen=True)
class LeaseConfig:
    """Failure-detection knobs."""

    # seconds a worker stays trusted after its last successful
    # heartbeat; expiry alone does NOT declare death (see probes)
    lease_s: float = 2.0
    # consecutive failed probes (after lease expiry) before death
    probe_retries: int = 3
    # probe pacing: capped exponential backoff with seeded jitter
    probe_base_ms: float = 50.0
    probe_cap_ms: float = 1000.0
    seed: int = 0

    def __post_init__(self):
        if self.lease_s <= 0 or self.probe_retries < 1:
            raise ValueError("need lease_s > 0 and probe_retries >= 1")


class _WorkerHealth:
    __slots__ = ("lease_until", "failures", "next_probe", "backoff")

    def __init__(self, now: float, lease_s: float, backoff: Backoff):
        self.lease_until = now + lease_s
        self.failures = 0
        self.next_probe = now
        self.backoff = backoff


class Membership:
    """Lease table + probe scheduler over a set of worker ids."""

    def __init__(
        self,
        config: LeaseConfig | None = None,
        *,
        clock: Callable[[], float] | None = None,
    ):
        self.config = config or LeaseConfig()
        self._clock = clock or time.monotonic
        self._health: dict = {}
        self._dead: list = []

    # ------------------------------------------------------ membership

    def add(self, worker_id) -> None:
        cfg = self.config
        self._health[worker_id] = _WorkerHealth(
            self._clock(),
            cfg.lease_s,
            Backoff(
                BackoffPolicy(
                    base_ms=cfg.probe_base_ms, cap_ms=cfg.probe_cap_ms
                ),
                seed=cfg.seed,
            ),
        )

    def remove(self, worker_id) -> None:
        self._health.pop(worker_id, None)

    def alive(self) -> tuple:
        return tuple(self._health)

    @property
    def dead(self) -> tuple:
        """Workers declared dead, in declaration order."""
        return tuple(self._dead)

    # ------------------------------------------------------- evidence

    def note_ok(self, worker_id) -> None:
        """A successful heartbeat/call: renew the lease, clear the
        suspicion state and restart the probe backoff schedule."""
        h = self._health.get(worker_id)
        if h is None:
            return
        h.lease_until = self._clock() + self.config.lease_s
        h.failures = 0
        h.next_probe = self._clock()
        h.backoff.reset()

    def note_failure(self, worker_id) -> None:
        """A failed heartbeat/call: count it and push the next probe
        out by the backoff schedule (capped — a long-dead worker is
        probed at the cap rate until the lease math declares it)."""
        h = self._health.get(worker_id)
        if h is None:
            return
        h.failures += 1
        h.next_probe = self._clock() + h.backoff.next_ms() / 1e3

    def note_timeout(self, worker_id) -> None:
        """A DEADLINE-EXCEEDED call (``WorkerTimeout``): the link is
        slow or the worker busy — re-pace the next probe by the same
        backoff schedule but consume NO probe strike and renew nothing.
        Connection-refused is death evidence (nobody listening);
        a late answer is congestion evidence, and a worker whose lease
        expires on congestion alone still needs ``probe_retries``
        REFUSED probes before the detector declares it — the
        slow-link partition case resolves with zero failovers."""
        h = self._health.get(worker_id)
        if h is None:
            return
        h.next_probe = self._clock() + h.backoff.next_ms() / 1e3

    def probe_due(self, worker_id) -> bool:
        """Should the controller spend a probe on this worker now?
        Healthy workers are always probe-due (the probe IS the
        heartbeat); suspected ones wait out their backoff."""
        h = self._health.get(worker_id)
        return h is not None and self._clock() >= h.next_probe

    def suspected(self, worker_id) -> bool:
        """True while the worker has unresolved probe failures — the
        controller probes these with the cheap ``heartbeat()`` RPC
        before spending a full poll on them."""
        h = self._health.get(worker_id)
        return h is not None and h.failures > 0

    def expired(self) -> tuple:
        """Workers whose lease ran out AND whose probe budget is spent
        — the death declarations.  Declared workers move to ``dead``
        and leave the health table (the controller removes them from
        the ring and starts the failover)."""
        now = self._clock()
        cfg = self.config
        newly = [
            wid
            for wid, h in self._health.items()
            if now >= h.lease_until and h.failures >= cfg.probe_retries
        ]
        for wid in newly:
            del self._health[wid]
            self._dead.append(wid)
        return tuple(newly)
