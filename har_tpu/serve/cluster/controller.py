"""The cluster control plane: N FleetServer workers behind one router,
with heartbeat failover and journal hand-off session migration.

``FleetServer`` is structurally one process — one crash takes the whole
fleet down, and the PR-4 journal can only recover it IN PLACE.  This
module partitions sessions across N worker processes (each an
unmodified ``FleetServer`` + journal directory) behind a consistent-
hash router, and turns the PR-4 recovery machinery into LIVE MIGRATION:

  placement   a consistent-hash ring (``router.py``) decides where a
              session is admitted and where a dead worker's sessions
              fail over to; the controller keeps the authoritative
              ``session → worker`` map on top (a migrated session stays
              pinned to its adopter even where the ring disagrees);

  detection   a heartbeat/lease protocol (``membership.py``): poll
              success renews a worker's lease; a worker that stops
              answering is probed at a capped-exponential-backoff
              cadence (``har_tpu.utils.backoff`` — the same policy the
              dispatch retry loop uses) and declared dead only after
              lease expiry AND the probe budget — no wall clocks, the
              injected clock drives everything (FakeClock in tests);

  failover    live session migration via journal hand-off: restore the
              dead worker's partition from its journal+snapshot (the
              PR-4 ``FleetServer.restore`` path), DRAIN it (score the
              recovered pending windows — acks land in the dead
              worker's journal, so a crash mid-failover re-drains
              idempotently, zero double-scored), then hand each session
              to its surviving ring owner: the target journals an
              ``adopt`` record with the full exported state BEFORE the
              source journals its ``handoff`` eviction, so a crash
              anywhere in the protocol leaves the session on >= 1
              journal and dual ownership resolves by the ``handoffs``
              generation.  The transport resumes delivery at
              ``watermark(sid)`` — migrated event streams are
              bit-identical to an unmigrated run (chaos-pinned);

  accounting  the conservation law extends CROSS-WORKER: summed over
              live workers plus the retired-worker ledger (each dead
              worker's final post-drain accounting, persisted in its
              ``retired.json`` marker), ``enqueued == scored + dropped
              + pending + lost_in_crash`` holds globally through any
              failover — ``accounting()`` is that sum, computed with
              the DrJAX-style ``map_fn``/``reduce_sum`` primitives;

  adaptation  drift evidence aggregates the same way: ``observe_drift``
              feeds every partition's reports into ONE RetrainTrigger,
              so K sessions drifting on a common channel escalate no
              matter how the router spread them across workers.

The control plane is asynchronous and bounded-retry by design (the
Spark-ML perf study, arXiv 1612.01437: coordination overhead, not
compute, dominates distributed ML): heartbeats ride the poll the
caller already makes, probes are backoff-paced, hand-offs retry a
bounded number of times — and none of it ever blocks a healthy
worker's dispatch hot path.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Hashable

from har_tpu.serve.cluster.membership import (
    LeaseConfig,
    Membership,
    WorkerTimeout,
    WorkerUnavailable,
)
from har_tpu.serve.cluster.primitives import map_fn, reduce_sum
from har_tpu.serve.cluster.router import ConsistentHashRouter
from har_tpu.serve.cluster.worker import ClusterWorker
from har_tpu.serve.engine import AdmissionError, FleetServer
from har_tpu.serve.journal import JournalConfig, JournalError
from har_tpu.utils.backoff import Backoff, BackoffPolicy, retry_call
from har_tpu.utils.durable import atomic_write

RETIRED_MARKER = "retired.json"


class ClusterError(RuntimeError):
    """Cluster-level invariant violated (no live target for a hand-off,
    unknown session, duplicate worker id)."""


class PartitionUnavailable(ClusterError):
    """A dead worker's journal could not be FETCHED right now (the
    shared-nothing deployment's ship agent is unreachable,
    har_tpu.serve.net.ship).  Not a failure: the failover PARKS on the
    fetch queue and retries at a later poll — survivors keep serving,
    the dead partition's disk state is untouched, and nothing is lost,
    only delayed."""


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Control-plane knobs: ring shape, failure detection, hand-off
    retry budget."""

    # virtual nodes per worker on the consistent-hash ring
    replicas: int = 64
    # heartbeat/lease failure detection (membership.py)
    lease_s: float = 2.0
    probe_retries: int = 3
    probe_base_ms: float = 50.0
    probe_cap_ms: float = 1000.0
    # transparent re-attempts of one session hand-off before trying the
    # next live worker (bounded: a hand-off must never spin)
    handoff_retries: int = 2
    seed: int = 0

    def lease_config(self) -> LeaseConfig:
        return LeaseConfig(
            lease_s=self.lease_s,
            probe_retries=self.probe_retries,
            probe_base_ms=self.probe_base_ms,
            probe_cap_ms=self.probe_cap_ms,
            seed=self.seed,
        )


class FleetCluster:
    """N journaled FleetServers behind a consistent-hash router.

    Duck-types the slice of ``FleetServer`` the load plane speaks
    (``push`` / ``poll`` / ``flush`` / ``watermark`` / ``hop``), so
    ``drive_fleet`` and the CLI drive a cluster exactly like a single
    server — the partitioning is invisible to the transport except
    when a hand-off moves a session's watermark.

    ``model`` serves every worker; ``loader`` (``version -> model``)
    resolves checkpoints during failover restores and defaults to
    serving ``model`` for every version.  ``fault_hook_for(worker_id)``
    builds per-worker dispatch fault hooks (chaos harness).
    """

    def __init__(
        self,
        model,
        root: str,
        *,
        workers: int = 3,
        window: int = 200,
        hop: int = 20,
        channels: int = 3,
        smoothing: str = "ema",
        fleet_config=None,
        journal_config: JournalConfig | None = None,
        config: ClusterConfig | None = None,
        clock: Callable[[], float] | None = None,
        loader: Callable | None = None,
        fault_hook_for: Callable | None = None,
        class_names=None,
        _workers: list | None = None,
        _ledger: list | None = None,
    ):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.config = config or ClusterConfig()
        self._clock = clock
        self._model = model
        self._loader = loader or (lambda version: model)
        self._fault_hook_for = fault_hook_for
        self._journal_config = journal_config
        self.hop = int(hop)
        self._router = ConsistentHashRouter(self.config.replicas)
        self._membership = Membership(
            self.config.lease_config(), clock=clock
        )
        self._workers: dict = {}
        self._placement: dict = {}  # session -> worker id
        self._ledger: list = list(_ledger or [])
        self.failovers = 0
        # wall time spent inside failover machinery (restore + drain +
        # hand-offs), the control plane's headline latency — bench-lane
        # observable, accumulated with perf_counter duration reads
        self.failover_ms = 0.0
        self.migration_log: list[dict] = []
        self._pending_events: list = []
        # failovers split across two polls: restore+drain returns its
        # events THIS poll; the hand-offs run at the START of the next
        # poll, when no acked events are in flight — so a controller
        # crash at the mid_migration/mid_handoff stage boundaries can
        # never strand an acked-but-undelivered event
        self._handoff_queue: list = []
        # failovers whose partition FETCH failed (shared-nothing ship
        # agent unreachable): (dead_wid, worker) pairs retried at each
        # poll — a dead worker whose host agent is also down parks here
        # while the survivors keep serving
        self._fetch_queue: list = []
        # hand-off retry pacing: the same Backoff policy family as the
        # dispatch retry loop (har_tpu.utils.backoff), seeded — the
        # control plane is deterministic under the chaos harness
        self._handoff_backoff = Backoff(
            BackoffPolicy(
                base_ms=self.config.probe_base_ms,
                cap_ms=self.config.probe_cap_ms,
            ),
            seed=self.config.seed,
        )
        # chaos hook (serve.chaos): raises a simulated crash at the two
        # migration stage boundaries the kill matrix exercises
        self.chaos: Callable[[str], None] | None = None
        # warm standbys (har_tpu.serve.replica.StandbyAgent): cycled at
        # the top of every poll so their tails stay caught up; a
        # failover consults them FIRST — a standby that holds the dead
        # worker's tail finalizes it (verify already-local bytes, pull
        # only the missing suffix) and the cold ship/shared-disk path
        # becomes the fallback.  name -> (standby, prefer_wid)
        self._standbys: dict = {}
        # bytes moved ON the failover path (finalize_tail pulls) — 0
        # for a caught-up tail, vs NetCluster.ship_ms/shipped_bytes
        # which count the steady-state tail + cold ships
        self.failover_path_bytes = 0
        self.standby_fetches = 0
        # dead_wid -> original journal dir for failovers restored from
        # a standby tail: the retired marker lands in the REPLICA dir
        # (the restore source), so commit propagates it back to the
        # original home a takeover/resume scan reads
        self._standby_origin: dict = {}
        if _workers is not None:
            for w in _workers:
                self._adopt_worker(w)
            self._rebuild_placement()
        else:
            os.makedirs(self.root, exist_ok=True)
            for i in range(int(workers)):
                wid = f"w{i}"
                self._adopt_worker(
                    ClusterWorker(
                        wid,
                        FleetServer(
                            model,
                            window=window,
                            hop=hop,
                            channels=channels,
                            smoothing=smoothing,
                            class_names=class_names,
                            config=fleet_config,
                            clock=clock,
                            fault_hook=(
                                fault_hook_for(wid)
                                if fault_hook_for is not None
                                else None
                            ),
                            journal=os.path.join(self.root, wid),
                            journal_config=journal_config,
                        ),
                        os.path.join(self.root, wid),
                    )
                )
        if not self._workers:
            raise ClusterError("a cluster needs at least one worker")

    # ------------------------------------------------------ membership

    def _adopt_worker(self, worker: ClusterWorker) -> None:
        if worker.worker_id in self._workers:
            raise ClusterError(
                f"duplicate worker id {worker.worker_id!r}"
            )
        self._workers[worker.worker_id] = worker
        self._router.add_worker(worker.worker_id)
        self._membership.add(worker.worker_id)

    @property
    def workers(self) -> tuple:
        return tuple(self._workers)

    @property
    def servers(self) -> tuple:
        """The live FleetServers, membership order — what the DrJAX
        primitives and the fleet-global drift trigger map over."""
        return tuple(w.server for w in self._workers.values())

    def worker_of(self, session_id: Hashable):
        wid = self._placement.get(session_id)
        if wid is None:
            raise ClusterError(f"unknown session {session_id!r}")
        return wid

    @property
    def sessions(self) -> tuple:
        return tuple(self._placement)

    def _chaos(self, point: str) -> None:
        if self.chaos is not None:
            self.chaos(point)

    def _note_worker_failure(self, wid, exc: WorkerUnavailable) -> None:
        """Route the two failure species to the detector: a DEADLINE
        (``WorkerTimeout`` — slow link / busy worker) re-paces the
        probe WITHOUT a strike; everything else (connection refused,
        reset — nobody home) counts toward the death verdict."""
        if isinstance(exc, WorkerTimeout):
            self._membership.note_timeout(wid)
        else:
            self._membership.note_failure(wid)

    # ------------------------------------------------------- data plane

    def add_session(self, session_id: Hashable, *, monitor=None) -> None:
        """Admit a session on its ring owner."""
        if session_id in self._placement:
            raise ClusterError(
                f"session {session_id!r} already admitted"
            )
        wid = self._router.owner(session_id)
        self._workers[wid].add_session(session_id, monitor=monitor)
        self._placement[session_id] = wid

    def disconnect_session(self, session_id: Hashable) -> list:
        """Graceful churn disconnect on the session's worker (elastic
        traffic, har_tpu.serve.traffic): the worker flushes the
        assembler's partial window, settles its pending queue (those
        events are returned — the worker's drain is fleet-local), and
        journals the eviction; the placement entry is dropped."""
        wid = self.worker_of(session_id)
        worker = self._workers.get(wid)
        if worker is None:
            raise WorkerUnavailable(
                f"session {session_id!r} is mid-failover"
            )
        try:
            events = worker.disconnect_session(session_id)
        except WorkerUnavailable as exc:
            self._note_worker_failure(wid, exc)
            raise
        self._membership.note_ok(wid)
        del self._placement[session_id]
        return events

    def disconnect_sessions(self, session_ids) -> list:
        """Batched graceful churn disconnect: leavers group by owning
        worker so each worker settles ONCE for its whole departing
        cohort (the storm case) instead of once per session."""
        by_worker: dict = {}
        for sid in session_ids:
            by_worker.setdefault(self.worker_of(sid), []).append(sid)
        events: list = []
        for wid, sids in by_worker.items():
            worker = self._workers.get(wid)
            if worker is None:
                raise WorkerUnavailable(
                    f"sessions {sids!r} are mid-failover"
                )
            try:
                events.extend(worker.disconnect_sessions(sids))
            except WorkerUnavailable as exc:
                self._note_worker_failure(wid, exc)
                raise
            self._membership.note_ok(wid)
            for sid in sids:
                del self._placement[sid]
        return events

    def push(self, session_id: Hashable, samples) -> int:
        """Route one delivery to the session's worker.  Fails FAST on
        an unreachable worker (``WorkerUnavailable``) — the evidence
        feeds the failure detector and the transport re-delivers from
        ``watermark(sid)`` once failover lands; the control plane never
        blocks a push on a sick peer."""
        wid = self.worker_of(session_id)
        worker = self._workers.get(wid)
        if worker is None:
            # mid-failover: the partition is being recovered; the
            # transport re-delivers from watermark(sid) once it lands
            raise WorkerUnavailable(
                f"worker {wid!r} is failing over"
            )
        try:
            n = worker.push(session_id, samples)
        except WorkerUnavailable as exc:
            self._note_worker_failure(wid, exc)
            raise
        self._membership.note_ok(wid)
        return n

    def push_many(self, session_ids, chunks) -> int:
        """Route one delivery ROUND (``FleetServer.push_many``'s
        signature — the load generators already speak it): pairs in
        delivery order, grouped by owning worker so each worker sees
        ONE batched ``push_many`` call (over the wire: one frame)
        instead of one per session.  Per-worker delivery order is the
        argument order, so windows enqueue exactly as the equivalent
        per-session ``push`` sequence would.  Fails fast like ``push``:
        an unreachable worker raises after earlier groups have landed —
        the transport re-delivers the failed partition from
        ``watermark(sid)`` once failover lands."""
        by_worker: dict = {}
        for sid, samples in zip(session_ids, chunks):
            wid = self.worker_of(sid)
            group = by_worker.setdefault(wid, ([], []))
            group[0].append(sid)
            group[1].append(samples)
        total = 0
        for wid, (ids, payloads) in by_worker.items():
            worker = self._workers.get(wid)
            if worker is None:
                raise WorkerUnavailable(
                    f"worker {wid!r} is failing over"
                )
            try:
                total += worker.push_many(ids, payloads)
            except WorkerUnavailable as exc:
                self._note_worker_failure(wid, exc)
                raise
            self._membership.note_ok(wid)
        return total

    def poll(self, *, force: bool = False) -> list:
        """Poll every responsive worker (the poll doubles as its
        heartbeat), run the failure detector, fail over any declared
        death, and return the fleet's events — survivors' dispatches
        are never blocked by a sick peer: suspected workers are skipped
        until their backoff-paced probe comes due.

        Stage order is the crash-safety argument: queued HAND-OFFS
        first (no events in flight yet — the window the chaos matrix's
        ``mid_migration``/``mid_handoff`` kills land in), then death
        declarations (restore + drain, whose events deliver with this
        poll's return), then the worker polls.  On any crash the
        already-collected events are stashed and delivered by the next
        poll — an acked event is returned exactly once."""
        events = self._pending_events
        self._pending_events = []
        try:
            # warm standbys tail first: the cycle that runs in the same
            # poll that declares a death sees the (now static) journal
            # in full, which is what makes the failover-path transfer
            # deterministically zero for a registered standby
            for standby, _prefer in self._standbys.values():
                standby.cycle()
            while self._handoff_queue:
                dead_wid, restored = self._handoff_queue[0]
                self._complete_failover(dead_wid, restored)
                self._handoff_queue.pop(0)
            if self._fetch_queue:
                # parked shared-nothing failovers: retry the partition
                # fetch (the dead host's ship agent may be back); a
                # still-unreachable agent re-parks without blocking the
                # survivors' polls below.  Entries are popped one at a
                # time so a crash mid-retry loses at most the IN-FLIGHT
                # entry (the controller-crash model; takeover re-derives
                # it from the agents) — never the not-yet-retried rest.
                retry, self._fetch_queue = self._fetch_queue, []
                try:
                    while retry:
                        dead_wid, worker = retry.pop(0)
                        events.extend(
                            self._continue_failover(dead_wid, worker)
                        )
                except BaseException:
                    self._fetch_queue.extend(retry)
                    raise
            for wid in self._membership.expired():
                events.extend(self._begin_failover(wid))
            for wid in list(self._workers):
                w = self._workers[wid]
                if not self._membership.probe_due(wid):
                    continue  # suspected: wait out the probe backoff
                if self._membership.suspected(wid):
                    # the due probe of a suspected worker is the cheap
                    # heartbeat RPC (no fleet state touched) — only a
                    # worker that answers it gets a full poll again
                    try:
                        w.heartbeat()
                    except WorkerUnavailable as exc:
                        self._note_worker_failure(wid, exc)
                        continue
                try:
                    evs = w.poll(force=force)
                except WorkerUnavailable as exc:
                    self._note_worker_failure(wid, exc)
                    continue
                self._membership.note_ok(wid)
                events.extend(evs)
        except BaseException:
            # a crash mid-poll (chaos SimulatedCrash from a worker's
            # journal hook or the migration machinery) must not lose
            # already-returned events — stash them; the next poll (or
            # the takeover controller) delivers them first
            self._pending_events = events
            raise
        return events

    def flush(self) -> list:
        return self.poll(force=True)

    def watermark(self, session_id: Hashable) -> int:
        worker = self._workers.get(self.worker_of(session_id))
        if worker is None:
            raise WorkerUnavailable(
                f"session {session_id!r} is mid-failover"
            )
        return worker.watermark(session_id)

    def swap_model(self, model, *, version: str) -> str:
        """Fleet-wide zero-drop hot swap: broadcast the new model to
        every live worker (each applies it at its own dispatch
        boundary, the PR-3 semantics).  Idempotent per worker — a
        re-issued broadcast after a mid-swap worker loss skips workers
        already serving ``version``, and a worker that dies mid-
        broadcast is failure-detector evidence, not a broadcast
        failure (the re-issued broadcast lands it post-failover)."""
        for wid in list(self._workers):
            w = self._workers[wid]
            if not w.alive:
                continue
            try:
                w.swap_model(model, version=version)
            except WorkerUnavailable as exc:
                self._note_worker_failure(wid, exc)
        return version

    def observe_drift(self, trigger) -> None:
        """Feed every partition's drift reports into one fleet-global
        RetrainTrigger (``RetrainTrigger.observe_workers``): K sessions
        drifting on a common channel escalate across workers."""
        trigger.observe_workers(self.servers)

    # --------------------------------------------------------- failover

    def _begin_failover(self, dead_wid) -> list:
        """Phase 1 of a declared death: fence the worker (refuse any
        late responses — the in-process stand-in for lease-based
        fencing), remove it from the ring, FETCH its partition
        (``_fetch_partition`` — the dead directory itself on a shared
        disk, a digest-verified shipped copy in the shared-nothing
        deployment), restore and DRAIN it — the recovered pending
        windows score through the restored engine (the PR-4 path; acks
        land durably in the restored journal, so a re-drain after a
        second crash re-emits nothing).  Returns the drained events;
        the hand-offs are queued for the next poll's phase 2."""
        worker = self._workers.pop(dead_wid)
        worker.kill()
        self._router.remove_worker(dead_wid)
        self.failovers += 1
        return self._continue_failover(dead_wid, worker)

    def _continue_failover(self, dead_wid, worker) -> list:
        """Fetch + restore + drain one declared-dead partition.  A
        fetch refusal (``PartitionUnavailable``) parks the pair on the
        fetch queue for the next poll; a fetch that reports the
        partition already consumed (retired marker on either side)
        ends the failover with nothing to do."""
        t0 = time.perf_counter()
        try:
            src = self._fetch_partition(worker)
        except PartitionUnavailable:
            self._fetch_queue.append((dead_wid, worker))
            return []
        if src is None:
            return []  # already consumed by an earlier controller
        # the verified partition is local and whole; the crash window
        # between the landed ship and the drain is its own kill point
        self._chaos("post_ship_pre_drain")
        restored = FleetServer.restore(
            src, self._loader, clock=self._clock
        )
        events = restored.flush()
        self.failover_ms += (time.perf_counter() - t0) * 1e3
        self._handoff_queue.append((dead_wid, restored))
        return events

    def _fetch_partition(self, worker) -> str | None:
        """Locate (or materialize) the dead worker's journal locally
        and return the directory to restore from; None when the
        partition was already consumed (retired).  The shared-disk
        default reads the directory in place; the shared-nothing
        transport (``har_tpu.serve.net.NetCluster``) overrides this
        with the journal-shipping RPC — raising
        ``PartitionUnavailable`` when the ship agent is unreachable."""
        marker = os.path.join(worker.journal_dir, RETIRED_MARKER)
        if os.path.exists(marker):
            return None
        dest = self._standby_partition(worker.worker_id)
        if dest is not None:
            self._standby_origin[worker.worker_id] = worker.journal_dir
            return dest
        return worker.journal_dir

    # ------------------------------------------------- warm standbys

    def register_standby(self, standby, *, name: str = "sb0",
                         prefer=None) -> None:
        """Attach a ``StandbyAgent`` whose tails this controller drives
        from its poll loop and consults first at failover.  ``prefer``
        names the worker co-located with the standby's replicas:
        failover hand-offs of a partition this standby holds are
        steered there ahead of the ring owner (warm placement — the
        adopter next to the already-local bytes)."""
        self._standbys[name] = (standby, prefer)

    def _standby_partition(self, dead_wid) -> str | None:
        """The warm path of a partition fetch: a standby holding the
        dead worker's tail finalizes it — whole-file sha256 on
        already-local bytes plus the missing suffix (zero bytes when
        the tail was caught up).  Any ship failure here falls back to
        the cold path (``None``): a broken standby must never make a
        failover WORSE than PR-14's ship-at-failover."""
        from har_tpu.serve.net.ship import ShipError

        for name, (standby, _prefer) in self._standbys.items():
            if not standby.holds(dead_wid):
                continue
            try:
                fin = standby.finalize(dead_wid)
            except ShipError:
                continue
            self.standby_fetches += 1
            self.failover_path_bytes += int(fin.get("bytes", 0))
            return standby.dest(dead_wid)
        return None

    def _warm_adopter(self, dead_wid):
        """The worker failover hand-offs should prefer for sessions of
        ``dead_wid`` — the one registered next to a standby that holds
        its replica; None when no standby claims it."""
        for standby, prefer in self._standbys.values():
            if prefer is not None and standby.holds(dead_wid):
                return prefer
        return None

    @property
    def pending_failovers(self) -> int:
        """Failovers parked on an unreachable partition fetch."""
        return len(self._fetch_queue)

    def _complete_failover(self, dead_wid, restored) -> None:
        """Phase 2: hand every drained session to the survivors, then
        commit the partition as consumed — final accounting into the
        ledger AND the dead directory's ``retired.json`` marker (what a
        takeover controller reads).  Idempotent: sessions the survivors
        already adopted are skipped, hand-off records make the source
        side re-derivable, and the marker is the commit point."""
        t0 = time.perf_counter()
        receivers = []
        # the restored partition wears the ordinary worker surface for
        # the hand-off (export_session/evict_session) — one evict body,
        # not a parallel wrapper that could drift from it
        source = ClusterWorker(dead_wid, restored, restored.journal.root)
        prefer = self._warm_adopter(dead_wid)
        for sid in restored.sessions:
            target_wid = self._hand_off(
                source, sid, dead_wid, prefer=prefer
            )
            if target_wid not in receivers:
                receivers.append(target_wid)
            self._chaos("mid_migration")
        self.failover_ms += (time.perf_counter() - t0) * 1e3
        for wid in receivers:
            self._workers[wid].note_failover_absorbed()
        self._ledger.append(
            {
                "worker_id": dead_wid,
                "accounting": restored.stats.accounting(),
                "scored_by_version": dict(
                    restored.stats.scored_by_version
                ),
            }
        )
        atomic_write(
            os.path.join(restored.journal.root, RETIRED_MARKER),
            json.dumps(self._ledger[-1]),
        )
        # shared-nothing hook: the transport controller also marks the
        # SOURCE copy retired on its home host (best-effort — the local
        # marker above is the commit point for this controller lineage)
        self._commit_retired(dead_wid, self._ledger[-1])
        restored.journal.close()

    def _commit_retired(self, dead_wid, entry: dict) -> None:
        """Transport hook: propagate a consumed partition's retired
        marker back to its source home.  In-process this only matters
        for a standby-sourced failover (the marker above landed in the
        REPLICA dir; a resume/takeover scan reads the original home);
        the wire transport overrides this with the agent's retire
        RPC."""
        origin = self._standby_origin.pop(dead_wid, None)
        if origin is not None and os.path.isdir(origin):
            atomic_write(
                os.path.join(origin, RETIRED_MARKER), json.dumps(entry)
            )

    def _hand_off(self, source, sid, source_wid, target_wid=None,
                  prefer=None):
        """Move one drained session from ``source`` to its ring owner
        (or the explicit ``target_wid`` of a planned move):
        adopt-first (durable on the target), chaos point in the
        dual-ownership window, then the source's journaled eviction.
        ``source`` speaks only ``export_session``/``evict_session`` —
        a live worker (in-process or RPC) or a ``_DrainedSource`` over
        a restored partition, transport-blind either way.  Bounded
        retries per target, then the next live worker — a hand-off
        never spins and never silently drops a session."""
        export = source.export_session(sid)
        if target_wid is not None:
            candidates = [target_wid]
        else:
            primary = self._router.owner(sid)
            candidates = [primary] + [
                wid for wid in self._workers if wid != primary
            ]
            if prefer is not None and prefer in self._workers:
                # warm placement: the adopter co-located with the
                # standby's replica of the source partition goes ahead
                # of the ring owner (the prior-durable-adopt pre-scan
                # below still wins over any preference)
                candidates = [prefer] + [
                    wid for wid in candidates if wid != prefer
                ]
        t0 = time.perf_counter()
        # ownership pre-scan over ALL live workers (the source of a
        # planned move excepted — it owns the session until its
        # eviction), before ANY adopt attempt: a prior (crashed)
        # attempt's durable adopt wins regardless of candidate order —
        # adopting a second live copy would fork the `handoffs`
        # generation ordering the dual-ownership resolution depends on
        target_wid = None
        for wid in self._workers:
            if wid != source_wid and self._workers[wid].owns(sid):
                target_wid = wid
                break
        if target_wid is None:
            for wid in candidates:
                worker = self._workers[wid]
                try:
                    # ClusterWorker.adopt is idempotent (skips the
                    # admit when the session already landed), so a
                    # retry after a flush failure completes the
                    # durability instead of tripping over
                    # "already admitted"
                    retry_call(
                        lambda: worker.adopt(export),
                        retries=self.config.handoff_retries,
                        backoff=self._handoff_backoff,
                        sleep=getattr(self._clock, "advance", None),
                    )
                except WorkerUnavailable as exc:
                    self._note_worker_failure(wid, exc)
                    continue
                except AdmissionError:
                    # target at its max_sessions cap: a capacity
                    # refusal is not a failure-detector signal — move
                    # on to the next live worker (the documented
                    # fallback)
                    continue
                target_wid = wid
                break
        if target_wid is None:
            raise ClusterError(
                f"no live worker could adopt session {sid!r}"
            )
        self._chaos("mid_handoff")
        source.evict_session(sid)
        target = self._workers[target_wid]
        target.note_migration_ms((time.perf_counter() - t0) * 1e3)
        self._placement[sid] = target_wid
        self.migration_log.append(
            {"sid": sid, "from": source_wid, "to": target_wid}
        )
        return target_wid

    # ---------------------------------------- planned rebalance / scale

    def migrate_session(self, session_id: Hashable, target_wid) -> None:
        """Planned live migration (rebalancing): hand the session to
        ``target_wid`` via the same adopt-first journal hand-off
        failover uses.  The caller drains first (``poll(force=True)``
        — its events are then already delivered); a session with live
        windows is refused by ``export_session``'s drain guard.  That
        ordering is the crash-safety argument: at the ``mid_handoff``
        stage boundary no acked event is in flight, so a controller
        crash there loses nothing — the session survives on >= 1
        journal and the takeover resolves ownership by generation."""
        src_wid = self.worker_of(session_id)
        if target_wid not in self._workers:
            raise ClusterError(f"unknown worker {target_wid!r}")
        if src_wid == target_wid:
            return
        source = self._workers[src_wid]
        self._hand_off(
            source, session_id, src_wid, target_wid=target_wid
        )

    def add_worker(
        self, worker_id=None, *, rebalance: bool = False
    ) -> str:
        """Scale up: a fresh journaled worker joins the ring; with
        ``rebalance`` the sessions whose arcs it now owns migrate over
        (drain → hand-off → resume, the same machinery)."""
        if worker_id is None:
            k = len(self._workers) + len(self._ledger)
            while f"w{k}" in self._workers:
                k += 1
            worker_id = f"w{k}"
        first = next(iter(self._workers.values())).server
        self._adopt_worker(
            ClusterWorker(
                worker_id,
                FleetServer(
                    self._model,
                    window=first.window,
                    hop=first.hop,
                    channels=first.channels,
                    smoothing=first.smoothing,
                    class_names=first.class_names,
                    config=first.config,
                    clock=self._clock,
                    fault_hook=(
                        self._fault_hook_for(worker_id)
                        if self._fault_hook_for is not None
                        else None
                    ),
                    journal=os.path.join(self.root, worker_id),
                    journal_config=self._journal_config,
                ),
                os.path.join(self.root, worker_id),
            )
        )
        if rebalance:
            self.rebalance()
        return worker_id

    def rebalance(self) -> int:
        """Migrate every session whose ring owner disagrees with its
        placement (after a scale-up, or drift from prior failovers).
        Returns the number of sessions moved.  Call after a
        ``poll(force=True)`` drain — a session with live windows is
        refused by the hand-off's drain guard (deliberately: draining
        here would strand acked-but-undelivered events in controller
        memory across the ``mid_handoff`` crash window)."""
        moved = 0
        for sid in list(self._placement):
            owner = self._router.owner(sid)
            if owner != self._placement[sid]:
                self.migrate_session(sid, owner)
                moved += 1
        return moved

    def retire_worker(self, worker_id) -> int:
        """Planned scale-down: hand every session of a DRAINED worker
        to the survivors' ring arcs, commit its final accounting to
        the ledger.  Returns the number of sessions moved.  Like
        ``migrate_session``, the caller drains first
        (``poll(force=True)``): a session with live windows is refused
        by the hand-off's drain guard, so no acked-but-undelivered
        event can sit in controller memory across the ``mid_handoff``
        crash window."""
        if worker_id not in self._workers:
            raise ClusterError(f"unknown worker {worker_id!r}")
        if len(self._workers) < 2:
            raise ClusterError("cannot retire the last worker")
        worker = self._workers[worker_id]
        # validate BEFORE mutating ring/membership: an undrained
        # session discovered mid-retire would otherwise strand the
        # worker outside the failure detector with its sessions
        # unreachable forever
        undrained = worker.undrained()
        if undrained:
            raise ClusterError(
                f"worker {worker_id!r} has live windows for sessions "
                f"{undrained[:5]}; drain (poll(force=True)) before "
                "retiring"
            )
        self._workers.pop(worker_id)
        self._router.remove_worker(worker_id)
        self._membership.remove(worker_id)
        moved = 0
        for sid in worker.sessions():
            self._hand_off(worker, sid, worker_id)
            moved += 1
        final = worker.final_accounting()
        self._ledger.append(
            {
                "worker_id": worker_id,
                "accounting": final["accounting"],
                "scored_by_version": final["scored_by_version"],
            }
        )
        atomic_write(
            os.path.join(worker.journal_dir, RETIRED_MARKER),
            json.dumps(self._ledger[-1]),
        )
        worker.close()
        return moved

    # --------------------------------------------------------- restart

    @classmethod
    def resume(
        cls,
        model,
        root: str,
        *,
        config: ClusterConfig | None = None,
        clock: Callable[[], float] | None = None,
        loader: Callable | None = None,
        fault_hook_for: Callable | None = None,
        journal_config: JournalConfig | None = None,
    ) -> "FleetCluster":
        """Restart a whole cluster from its journal directories (the
        controller and every worker died — a node loss).  Retired
        directories contribute their ledger entries; every other
        worker restores through the PR-4 path; sessions a crashed
        hand-off left on TWO journals resolve to the higher ``handoffs``
        generation (the adopter — adopt-first ordering guarantees the
        generations differ), and the loser's stale copy is evicted."""
        root = os.path.abspath(os.path.expanduser(root))
        the_loader = loader or (lambda version: model)
        workers: list[ClusterWorker] = []
        ledger: list[dict] = []
        for name in sorted(os.listdir(root)):
            jdir = os.path.join(root, name)
            if not os.path.isdir(jdir):
                continue
            marker = os.path.join(jdir, RETIRED_MARKER)
            if os.path.exists(marker):
                with open(marker) as f:
                    ledger.append(json.load(f))
                continue
            try:
                server = FleetServer.restore(
                    jdir,
                    the_loader,
                    clock=clock,
                    fault_hook=(
                        fault_hook_for(name)
                        if fault_hook_for is not None
                        else None
                    ),
                    journal_config=journal_config,
                )
            except JournalError:
                continue  # not a journal directory
            workers.append(ClusterWorker(name, server, jdir))
        cluster = cls(
            model,
            root,
            hop=workers[0].geometry()["hop"] if workers else 20,
            config=config,
            clock=clock,
            loader=loader,
            fault_hook_for=fault_hook_for,
            journal_config=journal_config,
            _workers=workers,
            _ledger=ledger,
        )
        return cluster

    @classmethod
    def takeover(
        cls,
        model,
        root: str,
        workers: list,
        *,
        config: ClusterConfig | None = None,
        clock: Callable[[], float] | None = None,
        loader: Callable | None = None,
        fault_hook_for: Callable | None = None,
        journal_config: JournalConfig | None = None,
    ) -> "FleetCluster":
        """Controller-only restart: the old controller crashed but the
        worker processes survived.  The new controller adopts the live
        ``ClusterWorker``s as they stand, re-derives placement from
        actual ownership (dual ownership from a crashed hand-off
        resolves by the ``handoffs`` generation), reads retired markers
        into the ledger, and COMPLETES any orphaned failover — a
        journal directory that is neither retired nor owned by a live
        worker is a partition whose migration the crash interrupted."""
        root = os.path.abspath(os.path.expanduser(root))
        ledger: list[dict] = []
        for name in sorted(os.listdir(root)):
            marker = os.path.join(root, name, RETIRED_MARKER)
            if os.path.isfile(marker):
                with open(marker) as f:
                    ledger.append(json.load(f))
        cluster = cls(
            model,
            root,
            hop=workers[0].geometry()["hop"] if workers else 20,
            config=config,
            clock=clock,
            loader=loader,
            fault_hook_for=fault_hook_for,
            journal_config=journal_config,
            _workers=workers,
            _ledger=ledger,
        )
        cluster._recover_orphans()
        return cluster

    def _recover_orphans(self) -> None:
        """Finish failovers a dead controller left half-done: restore,
        drain and hand off every journal directory no live worker owns
        and no retired marker has committed.  The drain's events ride
        ``_pending_events`` (acked durable before they queue, so a
        repeat crash re-derives rather than re-emits); the hand-offs
        are idempotent exactly like a first failover's."""
        owned = {w.journal_dir for w in self._workers.values()}
        for name in sorted(os.listdir(self.root)):
            jdir = os.path.join(self.root, name)
            if (
                not os.path.isdir(jdir)
                or jdir in owned
                or os.path.exists(os.path.join(jdir, RETIRED_MARKER))
            ):
                continue
            try:
                restored = FleetServer.restore(
                    jdir, self._loader, clock=self._clock
                )
            except JournalError:
                continue  # not a journal directory
            self.failovers += 1
            self._pending_events.extend(restored.flush())
            self._complete_failover(name, restored)

    def _rebuild_placement(self) -> None:
        """Restart-time ownership scan: resolve dual ownership (crash
        inside a hand-off window), then pin every session to the worker
        that actually holds it."""
        owners: dict = {}
        for wid, w in self._workers.items():
            for sid in w.sessions():
                owners.setdefault(sid, []).append(wid)
        for sid, wids in owners.items():
            if len(wids) > 1:
                # adopt-first ordering: generations strictly order the
                # copies; the highest is the adopted (newest) one
                wids.sort(
                    key=lambda wid: self._workers[wid].generation(sid)
                )
                keeper = wids[-1]
                for wid in wids[:-1]:
                    self._workers[wid].evict_session(sid)
                self._placement[sid] = keeper
            else:
                self._placement[sid] = wids[0]

    # ------------------------------------------------------- reporting

    def accounting(self) -> dict:
        """THE cross-worker conservation law: the element-wise sum of
        every live worker's accounting plus the retired-worker ledger.
        ``balanced`` requires every constituent to balance — a window
        double-counted or lost by a migration breaks a worker-level
        invariant before it could cancel out in the sums."""
        parts = map_fn(
            lambda w: w.accounting(), list(self._workers.values())
        )
        # a drained partition waiting on its phase-2 hand-offs is still
        # part of the global law (its windows are scored/pending THERE
        # until the ledger absorbs it)
        parts.extend(
            restored.stats.accounting()
            for _, restored in self._handoff_queue
        )
        parts.extend(entry["accounting"] for entry in self._ledger)
        total = reduce_sum(parts) if parts else {}
        total["workers"] = len(self._workers)
        total["retired_workers"] = len(self._ledger)
        return total

    def cluster_stats(self) -> dict:
        """Control-plane snapshot: global accounting, failover and
        migration evidence, per-worker session counts — aggregated with
        the same map/reduce primitives the drift escalation uses."""
        live = list(self._workers.values())
        # one control_stats round trip per worker (a transport-backed
        # worker pays one RPC here, not four)
        per_worker = map_fn(lambda w: w.control_stats(), live)
        return {
            "workers": len(live),
            "sessions": len(self._placement),
            "failovers": self.failovers,
            "failover_ms": round(self.failover_ms, 3),
            "failover_path_bytes": self.failover_path_bytes,
            "standbys": len(self._standbys),
            "standby_fetches": self.standby_fetches,
            "migrated_sessions": len(self.migration_log),
            "worker_failovers": reduce_sum(
                [p["worker_failovers"] for p in per_worker]
            ),
            "migrations": reduce_sum(
                [p["migrations"] for p in per_worker]
            ),
            "migration_ms": round(
                reduce_sum([p["migration_ms"] for p in per_worker]), 3
            ),
            "per_worker_sessions": {
                wid: p["sessions"]
                for wid, p in zip(self._workers, per_worker)
            },
            "accounting": self.accounting(),
            "retired": [e["worker_id"] for e in self._ledger],
        }

    def close(self) -> None:
        """Close every worker journal, including a restored partition
        still parked in the hand-off queue (its drain is durable; a
        later ``resume``/``takeover`` completes the migration).  Any
        still-stashed events were acked durable by their workers —
        abandoned here, never double-emitted on a later restore."""
        while self._handoff_queue:
            _, restored = self._handoff_queue.pop(0)
            if restored.journal is not None:
                restored.journal.close()
        for w in self._workers.values():
            w.close()
        for standby, _prefer in self._standbys.values():
            standby.close()
