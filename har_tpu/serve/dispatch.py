"""Pipelined, mesh-shardable batch dispatch for the fleet engine.

The PR-2 engine was structurally single-device and host-synchronous:
every ``_dispatch_batch`` stacked per-window arrays, scored on one
device, and BLOCKED on the result fetch before assembling the next
batch — the whole mesh idled while the host smoothed, and the host
idled while the chip scored.  The Spark-ML performance literature
(arXiv 1612.01437, PAPERS.md) attributes most distributed-ML loss to
exactly this serialization/scheduling overhead, not to compute; DrJAX's
sharded-map primitives point at the JAX-native fix.  This module is
that fix, in three pieces the engine composes:

  ``StagingArena`` — a preallocated contiguous ``(capacity, window,
    channels)`` staging block for queued windows.  The assembler writes
    each completed window into an arena slot ONCE at enqueue time
    (``_WindowAssembler.consume(sink=arena)``); batch assembly later is
    a single gather out of the block instead of ``np.stack`` over k
    scattered per-window allocations.

  ``DispatchTicket`` + the scorer family — the launch/retire split.
    ``launch(windows)`` stages the batch on-device (``jax.device_put``
    + the jitted predict) and returns WITHOUT fetching: the ticket
    holds the un-fetched device array while the host assembles the next
    batch.  ``fetch(handle, k)`` blocks on the result and produces the
    same ``(k, C)`` float64 probabilities the synchronous path did.
    Three scorers, one contract:

      ``HostScorer``    — ``model.transform`` verbatim (numpy stubs,
                          trees, exported artifacts): launch computes
                          synchronously, retire is a slice.  The
                          fallback that keeps every PR-2/3/4 behavior
                          bit-identical for host models.
      ``DeviceScorer``  — models with a jitted predict (``_predict`` +
                          ``params``, the NeuralModel family): host-side
                          scaler at launch, async jit dispatch, logits
                          fetched and softmaxed at retire with the SAME
                          ops ``NeuralModel.transform`` uses — probs are
                          bit-identical to the synchronous path.
      ``ShardedScorer`` — a DeviceScorer whose input is placed batch-
                          sharded over a ``jax.sharding.Mesh``
                          (``parallel.sharding.batch_sharding``); GSPMD
                          splits the row dimension across the mesh's
                          data axes.  Batches pad to ``devices × pow2``
                          (``serving.pad_shard``), so per device count
                          the compiled-program budget stays the same
                          log2 ladder the single-device policy pins.

The pipelining itself (double-buffered launch→retire with FIFO retire
order) lives in ``FleetServer.poll`` — retire order is the journal's
ack order, so the durability contract is untouched: a ticket in flight
at crash time is un-acked BY CONSTRUCTION and recovery re-scores its
windows from the replayed pushes.
"""

from __future__ import annotations

import time
import weakref

import numpy as np

from har_tpu.serving import pad_pow2, pad_shard

# fused-program fallback cache for inner objects that refuse instance
# attributes.  The PRIMARY cache is an attribute ON the inner model
# itself (``_har_fused_cache``): the fused jit belongs to the model —
# like ``_predict`` — so a rebuilt FleetServer, bench re-run or swap
# back reuses the compiled program, and the cache dies WITH the model
# (the value→model reference is an ordinary gc-collectable cycle).  A
# weak-key table cannot deliver that lifetime here: real checkpoint
# inners (``NeuralModel._predict`` is a jit of a lambda over ``self``)
# would be pinned by their own cached closure and never evict.  One
# cached jit serves every placement (pjit specializes per input
# sharding); entries hold (pre, jit) pairs compared by scaler identity
# (scalers carry ndarrays — unhashable).
_FUSED_PROGRAMS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


class DispatchTicket:
    """One launched, not-yet-retired batch.

    Carries everything retire needs without touching engine state that
    may have moved on (a model swap at a later launch boundary must not
    re-score an in-flight batch): the pending entries, the padded host
    windows (retry + dispatch-tap input), the un-fetched device handle,
    the scorer and model version that launched it, and the launch-time
    clock reads the dispatch/overlap stats are computed from.
    """

    __slots__ = (
        "batch", "k", "pad_k", "windows", "handle", "scorer", "version",
        "t0", "t_inflight0", "t_carried0", "idle_ms", "attempts",
        "failed", "last_error", "fused", "slab", "sids",
    )

    def __init__(self, batch, windows, scorer, version, t0, *,
                 fused: bool = False, slab=None):
        # ``batch`` is the int64 index array of the ticket's pending-
        # arena slots (har_tpu.serve.arena.PendingArena), in FIFO
        # order; the ticket owns the queue-side reference on each
        # until retire releases it.  ``sids`` is the launch-time
        # session-id snapshot the dispatch tap consumes (captured only
        # when a tap is installed — retire could not resolve a row
        # whose session was removed mid-flight).
        self.batch = batch
        self.k = len(batch)
        self.pad_k = len(windows)
        self.windows = windows
        self.handle = None
        self.scorer = scorer
        self.version = version
        self.t0 = t0
        self.t_inflight0 = t0
        self.t_carried0 = None  # set when the ticket survives its poll
        # fused hot-loop ticket: the handle is the small (labels,
        # top_probs) device pair, and ``windows`` is a pooled staging
        # slab the engine returns to its free pool at retire (the slab
        # stays valid for the whole flight — retries and the dispatch
        # tap read it — and is only recycled after the tap has run)
        self.fused = fused
        self.slab = slab
        self.sids = None
        # deliberate carry idle (inter-poll span) accumulated before
        # retire: excluded from dispatch_ms, so the SLO ladder never
        # reads the pipeline's own buffering as a slow tunnel
        self.idle_ms = 0.0
        self.attempts = 0  # FAILED attempts so far (retry budget used)
        self.failed = False
        self.last_error: Exception | None = None


class StagingArena:
    """Contiguous staging storage for queued windows.

    Slots recycle through a FIFO free ring (an int index ring, not a
    Python list): allocation hands out slots in the order retires
    returned them, which — because enqueue order IS launch order IS
    retire order in this engine — keeps a delivery round's staged
    windows CONTIGUOUS in the buffer in steady state.  That contiguity
    is what the zero-copy batch-assembly fast path rides: ``gather``
    returns a slice VIEW (no copy at all) and ``gather_into``
    degenerates to one contiguous block copy (no ``np.take``) whenever
    the requested slots form an ascending run; fragmented rounds
    (drops, sheds, churn punch holes in the recycle order) fall back
    to the scatter-gather path and re-converge on the next cycle.  The
    block grows geometrically when the queue outruns it (amortized —
    steady-state serving never reallocates).

    A VIEW handed to a dispatch is only safe because slot frees are
    retire-ordered: the engine frees a launched window's slot at its
    ticket's retire (after the blocking fetch — the same ordering the
    fused slab pool relies on), never mid-flight, so no re-``put`` can
    rewrite rows an un-fetched device array still aliases (CPU
    ``device_put`` aliases contiguous f32 buffers).  Growth mid-flight
    is also safe: the old buffer stays alive — and immutable — behind
    any view that still references it.
    """

    def __init__(self, window: int, channels: int, capacity: int = 512):
        self.window = int(window)
        self.channels = int(channels)
        capacity = max(int(capacity), 8)
        self._buf = np.empty(
            (capacity, self.window, self.channels), np.float32
        )
        # FIFO free ring: pow2 index buffer, monotonic head/tail
        self._free = np.empty(
            1 << (capacity - 1).bit_length(), np.int64
        )
        self._free[:capacity] = np.arange(capacity)
        self._fhead = 0
        self._ftail = capacity
        self.grows = 0

    @property
    def capacity(self) -> int:
        return len(self._buf)

    @property
    def in_use(self) -> int:
        return len(self._buf) - (self._ftail - self._fhead)

    @property
    def nbytes(self) -> int:
        """Resident bytes of the staging block (free ring included) —
        the ``staging_bytes`` footprint gauge's source."""
        return int(self._buf.nbytes) + int(self._free.nbytes)

    # ------------------------------------------------- free-slot ring

    def _free_extend(self, slots) -> None:
        m = len(slots)
        cap = len(self._free)
        if self._ftail - self._fhead + m > cap:  # pragma: no cover
            raise AssertionError("staging free-ring overflow")
        t = self._ftail & (cap - 1)
        first = min(cap - t, m)
        self._free[t: t + first] = slots[:first]
        if first < m:
            self._free[: m - first] = slots[first:]
        self._ftail += m

    def _free_popn(self, m: int) -> np.ndarray:
        cap = len(self._free)
        h = self._fhead & (cap - 1)
        first = min(cap - h, m)
        out = np.empty(m, np.int64)
        out[:first] = self._free[h: h + first]
        if first < m:
            out[first:] = self._free[: m - first]
        self._fhead += m
        return out

    def _grow(self, need: int = 0) -> None:
        """Double the block — or jump straight past ``need`` total
        slots in ONE reallocation: a 10k-session delivery round staging
        its whole window cohort must not pay log2 copies of the buffer
        on the way up (the SoA host plane's arena-sizing contract)."""
        cap = len(self._buf)
        new_cap = cap * 2
        while new_cap < need:
            new_cap *= 2
        buf = np.empty((new_cap, self.window, self.channels), np.float32)
        buf[:cap] = self._buf
        self._buf = buf
        n_free = self._ftail - self._fhead
        free = np.empty(1 << (new_cap - 1).bit_length(), np.int64)
        if n_free:
            free[:n_free] = self._free_popn(n_free)
        free[n_free: n_free + new_cap - cap] = np.arange(cap, new_cap)
        self._free = free
        self._fhead = 0
        self._ftail = n_free + new_cap - cap
        self.grows += 1

    def put(self, window: np.ndarray) -> int:
        """Stage one ``(window, channels)`` snapshot; returns its slot."""
        if self._ftail == self._fhead:
            self._grow()
        slot = self._free[self._fhead & (len(self._free) - 1)]
        self._fhead += 1
        self._buf[slot] = window
        return slot

    def put_block(self, windows: np.ndarray) -> np.ndarray:
        """Stage a ``(m, window, channels)`` block in one vectorized
        copy (the assembler's catch-up-burst path and the batched
        ``push_many`` round staging); returns the slots (an int64
        array — FIFO-recycled, so in steady state an ascending run)."""
        m = len(windows)
        if self._ftail - self._fhead < m:
            self._grow(self.in_use + m)
        slots = self._free_popn(m)
        s0 = self._run_start(slots)
        if s0 is not None:  # FIFO steady state: one basic-slice write
            self._buf[s0: s0 + m] = windows
        else:
            self._buf[slots] = windows
        return slots

    def reserve(self, m: int) -> np.ndarray:
        """Claim ``m`` slots off the FIFO free ring WITHOUT writing —
        the batched ingest reserves a whole delivery round's slots up
        front in DELIVERY order (the FIFO enqueue order), then each
        boundary-offset subgroup writes into its mapped subset
        (``put_block_pair(slots=...)``).  Assigning slots in delivery
        order is what keeps the launch-side gather a contiguous run —
        and therefore zero-copy — even when the round spans many
        subgroups."""
        if self._ftail - self._fhead < m:
            self._grow(self.in_use + m)
        return self._free_popn(m)

    def put_block_pair(
        self, head: np.ndarray, tail: np.ndarray, slots=None
    ) -> np.ndarray:
        """Stage a block of windows whose rows are each split in two
        contiguous parts — ``head[i] ++ tail[i]`` — writing BOTH parts
        straight into the staging storage (no intermediate
        concatenation).  The batched ingest path's mid-chunk window
        snapshots arrive exactly like this: the ring tail up to the
        boundary plus the chunk head that completes the window.
        ``slots`` uses pre-``reserve``d slots instead of popping."""
        m = len(head)
        if slots is None:
            if self._ftail - self._fhead < m:
                self._grow(self.in_use + m)
            slots = self._free_popn(m)
        split = head.shape[1]
        s0 = self._run_start(slots)
        if s0 is not None:  # FIFO steady state: basic-slice writes
            rows = self._buf[s0: s0 + m]
            if split:
                rows[:, :split] = head
            rows[:, split:] = tail
            return slots
        if split:
            self._buf[slots, :split] = head
        self._buf[slots, split:] = tail
        return slots

    def free(self, slot: int) -> None:
        cap = len(self._free)
        if self._ftail - self._fhead >= cap:  # pragma: no cover
            raise AssertionError("staging free-ring overflow")
        self._free[self._ftail & (cap - 1)] = slot
        self._ftail += 1

    def free_block(self, slots) -> None:
        """Vectorized retire-order free: a whole batch's slots return
        to the FIFO ring in one slice write, in their original enqueue
        order — the recycling discipline that keeps future rounds
        contiguous."""
        if len(slots):
            self._free_extend(slots)

    @staticmethod
    def _run_start(idx: np.ndarray):
        """First slot of an ascending +1 run covering the whole index
        array, or None when the request is fragmented — the zero-copy
        eligibility check (host-side index arithmetic throughout)."""
        k = len(idx)
        if not k:
            return None
        s0 = idx[0]
        if idx[k - 1] - s0 != k - 1:
            return None
        if k > 2 and not (idx[1:] - idx[:-1] == 1).all():
            return None
        return s0

    def gather_view(self, slots) -> np.ndarray | None:
        """The zero-copy batch: a slice VIEW over the staged rows when
        ``slots`` is one ascending run (the FIFO-recycled steady
        state), None when fragmented — the fused launch's exact-fit
        path, which then skips the slab entirely."""
        # host-side index-array build (no device fetch)
        # harlint: host-ok
        idx = np.asarray(slots, np.intp)
        s0 = self._run_start(idx)
        if s0 is None:
            return None
        return self._buf[s0: s0 + len(idx)]

    def gather(self, slots) -> np.ndarray:
        """One ``(k, window, channels)`` batch out of the block.  A
        FIFO-contiguous slot run returns a slice VIEW — the staged
        bytes themselves, zero copies (valid until the slots are freed
        AND re-``put``, which retire-ordered freeing defers past the
        dispatch that consumes it); fragmented requests fall back to
        the fancy-index copy."""
        # the slot list is a host-side index array; this is the index-
        # array build for the gather, not a device fetch
        # harlint: host-ok
        idx = np.asarray(slots, np.intp)
        s0 = self._run_start(idx)
        if s0 is not None:
            return self._buf[s0: s0 + len(idx)]
        return self._buf[idx]

    def gather_into(self, slots, out: np.ndarray) -> np.ndarray:
        """Gather ``slots`` into the first ``len(slots)`` rows of a
        PREALLOCATED ``out`` slab and pad the tail by repeating the last
        gathered row — the batch-assembly path of the fused dispatch
        hot loop.  A FIFO-contiguous run degenerates to one contiguous
        block copy (no ``np.take`` scatter-gather); ``out`` must
        already be sized to the scorer's padded shape, and the
        exact-fit case (``len(slots) == len(out)``) skips the tail
        fill entirely."""
        k = len(slots)
        # host-side index-array build, same as gather (no device fetch)
        # harlint: host-ok
        idx = np.asarray(slots, np.intp)
        s0 = self._run_start(idx)
        if s0 is not None:
            out[:k] = self._buf[s0: s0 + k]
        else:
            np.take(self._buf, idx, axis=0, out=out[:k])
        if k < len(out):
            out[k:] = out[k - 1]
        return out

    def state(self) -> dict:
        """Snapshot-provider payload: sizing observability only — the
        staged windows themselves ride the snapshot's existing
        ``pending`` array (gathered at snapshot time), so the on-disk
        format is unchanged and pre-arena journals restore cleanly."""
        return {
            "capacity": self.capacity,
            "in_use": self.in_use,
            "grows": self.grows,
        }


def compact_probs(
    labels: np.ndarray, top: np.ndarray, n_classes: int
) -> np.ndarray:
    """Decision-confidence surrogate distribution for the fused tier.

    The fused program retires only ``(labels, top_probs)`` — the full
    probability matrix never leaves the device.  Downstream consumers
    (vote/passthrough smoothing, events, journal acks, the shadow tap)
    still speak ``(k, C)`` distributions, so this rebuilds one on host:
    ``out[i, labels[i]] = top[i]`` and the remaining mass spread evenly
    over the other classes.  Two guarantees the retire path relies on:

      - ``argmax(out[i]) == labels[i]`` STRICTLY — the off-label mass is
        capped just below the top probability, so a journal replay that
        re-derives the raw label by argmax can never flip it on an
        exact ``top == 1/C`` tie;
      - ``out[i].sum()`` is 1 up to fp rounding, and ``out[i, labels[i]]``
        is exactly the device's top probability — the decision
        confidence every consumer reads is the real one.

    The off-label values are a surrogate (the fused tier's contract is
    LABEL equality with the unfused path, documented in serving.md);
    anything needing the true full distribution serves unfused.
    """
    k = len(labels)
    labels = np.asarray(labels, np.intp)
    top = np.asarray(top, np.float64)
    if n_classes <= 1:
        return np.ones((k, 1), np.float64)
    off = np.minimum(
        (1.0 - top) / (n_classes - 1),
        top * (1.0 - 2.0**-40),
    )
    out = np.repeat(off[:, None], n_classes, axis=1)
    out[np.arange(k), labels] = top
    return out


# --------------------------------------------------------------- scorers


class HostScorer:
    """``model.transform`` verbatim — the synchronous fallback.

    launch() computes the whole predict on the spot (host models have
    nothing to overlap), so depth-1 pipelining through this scorer is
    operation-for-operation the PR-2 synchronous engine: same transform
    call, same slice, same float64 cast.
    """

    kind = "host"
    devices = 1
    model_axis_shards = 1
    device_labels = ("host",)
    supports_fused = False  # no device program to fuse into

    def __init__(self, model):
        self.model = model
        self.compiled_shapes: set[int] = set()

    def pad(self, windows: np.ndarray) -> np.ndarray:
        return pad_pow2(windows)

    def pad_size(self, k: int) -> int:
        return 1 << (max(int(k), 1) - 1).bit_length()

    def launch(self, windows: np.ndarray):
        self.compiled_shapes.add(len(windows))
        return self.model.transform(windows).probability

    def fetch(self, handle, k: int) -> np.ndarray:
        return np.asarray(handle[:k], np.float64)  # harlint: fetch-ok

    def measure(self, batch: int, iters: int = 16, *,
                fused: bool = False) -> dict:
        raise ValueError(
            "device timing needs a jitted predict "
            f"(got host-side {type(self.model).__name__}); "
            "e2e latency stats are still available"
        )


def _split_predict(model):
    """Decompose a serving model into ``(host_pre, device_fn)`` — the
    host-side input transform (fitted scaler, or None) and the jitted
    logits program behind it.  Only the ``scaler + inner`` chain
    (NeuralClassifierModel over NeuralModel) is unwrapped: that chain's
    ``transform`` is exactly scaler → jitted logits → softmax, which the
    async path replicates bit-identically.  Exported StableHLO
    artifacts (ExportedPredictor) unwrap through their
    ``serving_inner()`` adapter — the deserialized program dispatches
    through the same async ticket path.  Wrappers that post-process
    the logits on host (temperature scaling) are NOT unwrapped — they
    serve through HostScorer, whose launch IS their ``transform``.
    Raises ValueError when no such chain exists (trees, MLlib
    replicas, numpy stubs)."""
    pre = None
    inner = model
    for _ in range(4):
        if hasattr(inner, "_predict") and hasattr(inner, "params"):
            return pre, inner
        if hasattr(inner, "serving_inner"):
            # exported StableHLO artifact (export.ExportedPredictor):
            # its adapter exposes the same (_predict, params) pair over
            # the deserialized program — the int8 weight-input form
            # ships its weights to the device once, at adapter build
            return pre, inner.serving_inner()
        nxt = getattr(inner, "inner", None)
        if nxt is None:
            break
        pre = getattr(inner, "scaler", None) or pre
        inner = nxt
    raise ValueError(
        "async dispatch needs a NeuralModel-backed classifier "
        f"(got {type(model).__name__})"
    )


class DeviceScorer:
    """Async launch/retire over a jitted predict.

    launch = host scaler + ``jax.device_put`` + the jitted logits call —
    returns the un-fetched device array (JAX dispatch is async; the
    device executes while the host moves on).  fetch = block on the
    logits, then the SAME softmax expression ``NeuralModel.transform``
    uses, so the probabilities are bit-identical to the synchronous
    path for the same model and batch.
    """

    kind = "device"

    def __init__(self, model):
        import jax

        self._jax = jax
        self.model = model
        self._pre, self._inner = _split_predict(model)
        # the param tree every launch/measure dispatches against —
        # subclasses that PLACE params (ModelParallelScorer's rule-table
        # layout) override this once at construction, and every
        # downstream path (bare predict, fused hot loop, calibration)
        # serves the placed tree without knowing it
        self._params = self._inner.params
        self.devices = 1
        self.device_labels = (str(jax.devices()[0].id),)
        self.compiled_shapes: set[int] = set()
        # the fused hot-loop program (built lazily at the first fused
        # launch): scale + logits + softmax + argmax + top-prob in ONE
        # jitted program per padded shape, retire fetching only the
        # small (labels, top_probs) pair.  Artifact-backed inners opt
        # out (an exported StableHLO call is not re-traceable inside a
        # surrounding jit on every jax version this repo supports).
        self.supports_fused = getattr(self._inner, "supports_fused", True)
        self._fused = None
        # emulated remote-tunnel round trip (a MODEL attribute, so the
        # engine stays knob-free): on a dry-run CPU mesh the local
        # "device" finishes in microseconds, while the documented
        # production path dispatches through a remote tunnel whose
        # ~hundreds-of-ms RTT is wait, not host CPU (BENCH_r04 serving
        # lane: ~250 ms e2e vs sub-ms device compute).  A model that
        # sets ``tunnel_rtt_ms`` makes fetch block until launch+RTT —
        # the wait pipelining exists to hide, reproducible on any host.
        self.tunnel_rtt_ms = float(
            getattr(model, "tunnel_rtt_ms", 0.0) or 0.0
        )

    def pad(self, windows: np.ndarray) -> np.ndarray:
        return pad_pow2(windows)

    def pad_size(self, k: int) -> int:
        return 1 << (max(int(k), 1) - 1).bit_length()

    def _place(self, x: np.ndarray):
        return self._jax.device_put(x)

    def launch(self, windows: np.ndarray):
        self.compiled_shapes.add(len(windows))
        x = windows if self._pre is None else self._pre.transform(windows)
        # cast of the host-side scaler's float64 output before
        # device_put; no device buffer is touched
        # harlint: host-ok
        x = self._place(np.asarray(x, np.float32))
        handle = self._inner._predict(self._params, x)
        if self.tunnel_rtt_ms:
            return (handle, time.perf_counter())
        return handle

    def fetch(self, handle, k: int) -> np.ndarray:
        if self.tunnel_rtt_ms:
            handle, t_launch = handle
            # the emulated tunnel: the result is not fetchable before
            # launch + RTT.  A retire that arrives later (the host was
            # assembling the next batch) waits for only the remainder —
            # exactly how a pipelined real tunnel behaves.
            wait = self.tunnel_rtt_ms / 1e3 - (
                time.perf_counter() - t_launch
            )
            if wait > 0:
                time.sleep(wait)
        jnp = self._jax.numpy
        logits = np.asarray(handle)  # harlint: fetch-ok (THE fetch)
        probs = np.asarray(  # harlint: fetch-ok
            self._jax.nn.softmax(jnp.asarray(logits), axis=-1)
        )
        return np.asarray(probs[:k], np.float64)  # harlint: fetch-ok

    # ------------------------------------------------- fused hot loop

    def _fused_fn(self):
        """THE fused device program: scale → logits → softmax → argmax
        + top-prob, one jit, one compile per padded shape.  The staged
        batch is DONATED where the backend can reuse buffers (donation
        is a no-op on the CPU dev mesh, which would only warn about the
        unusable donation — so it is requested on accelerator backends
        only); retire then fetches the small ``(labels, top_probs)``
        pair instead of the full ``(pad_k, C)`` logits matrix.  The
        scaler runs ON DEVICE here in f32 (the unfused path standardizes
        host-side): elementwise and deterministic, so labels — the
        fused tier's contract — are unchanged."""
        if self._fused is None:
            jax = self._jax
            jnp = jax.numpy
            inner = self._inner
            pre = self._pre
            entries = getattr(inner, "_har_fused_cache", None)
            if entries is None:
                entries = []
                try:
                    # cache ON the model: same lifetime as _predict —
                    # dropped incumbents take their compiled fused
                    # program with them (see _FUSED_PROGRAMS note)
                    inner._har_fused_cache = entries
                except (AttributeError, TypeError):
                    entries = _FUSED_PROGRAMS.setdefault(inner, [])
            for entry_pre, fn in entries:
                if entry_pre is pre:
                    self._fused = fn
                    return fn
            mean = None if pre is None else jnp.asarray(pre.mean)
            std = None if pre is None else jnp.asarray(pre.std)
            predict = inner._predict

            def fused(params, x):
                x = x.astype(jnp.float32)
                if mean is not None:
                    x = (x - mean) / std
                logits = predict(params, x)
                probs = jax.nn.softmax(
                    logits.astype(jnp.float32), axis=-1
                )
                labels = jnp.argmax(probs, axis=-1).astype(jnp.int32)
                return labels, jnp.max(probs, axis=-1)

            donate = () if jax.default_backend() == "cpu" else (1,)
            self._fused = jax.jit(fused, donate_argnums=donate)
            entries.append((pre, self._fused))
        return self._fused

    def launch_fused(self, windows: np.ndarray):
        """Fused launch: place the staged slab (already f32, already
        padded — the engine's slab pool guarantees both) and dispatch
        the one fused program, un-fetched.  No host-side scaler, no
        dtype cast, no per-dispatch allocation on this path."""
        self.compiled_shapes.add(len(windows))
        handle = self._fused_fn()(self._params, self._place(windows))
        if self.tunnel_rtt_ms:
            return (handle, time.perf_counter())
        return handle

    def fetch_fused(self, handle, k: int) -> tuple[np.ndarray, np.ndarray]:
        """Retire side of the fused program: block on — and transfer —
        only ``k`` int32 labels and ``k`` f32 top-probabilities
        (``8 × pad_k`` bytes instead of the unfused ``4 × pad_k × C``
        logits fetch; the saving is counted in
        ``FleetStats.fetch_bytes_saved``)."""
        if self.tunnel_rtt_ms:
            handle, t_launch = handle
            wait = self.tunnel_rtt_ms / 1e3 - (
                time.perf_counter() - t_launch
            )
            if wait > 0:
                time.sleep(wait)
        labels, top = handle
        labels = np.asarray(labels)  # harlint: fetch-ok (THE fetch)
        top = np.asarray(top)  # harlint: fetch-ok
        return (
            labels[:k].astype(np.int64),
            np.asarray(top[:k], np.float64),  # harlint: fetch-ok
        )

    def program_count(self) -> int | None:
        """Compiled-program count across the jits this scorer actually
        dispatches — the bare logits predict AND the fused hot-loop
        program when one has been built (a fused engine compiles its
        shapes on the fused jit and never calls ``_predict``, so
        counting only the latter would leave the compile-budget pin
        blind for the fused tier)."""
        total, found = 0, False
        for fn in (self._inner._predict, self._fused):
            if fn is None:
                continue
            try:
                total += int(fn._cache_size())
                found = True
            except (AttributeError, TypeError):
                pass
        return total if found else None

    def params_bytes(self) -> dict:
        """Host-side params-residency accounting: total checkpoint
        bytes and the largest single-device share.  A single-device (or
        batch-only-sharded) program holds the FULL param tree on every
        device; the model-parallel subclass divides each leaf by its
        spec's shard count.  Pure host arithmetic over leaf shapes —
        no device buffer is touched."""
        total = sum(
            # nbytes is shape×itemsize metadata on host and device
            # arrays alike — no transfer
            int(
                np.prod(np.shape(leaf), dtype=np.int64)
                * np.dtype(leaf.dtype).itemsize
            )
            for leaf in self._jax.tree.leaves(self._inner.params)
        )
        return {"total": total, "per_device": total}

    def measure(self, batch: int, iters: int = 16, *,
                fused: bool = False) -> dict:
        """Device p50 for one padded program AT THE SHAPE AND PLACEMENT
        the dispatch path actually emits — device-resident (sharded,
        for ShardedScorer) input, ``block_until_ready``, no fetch.

        ``fused=True`` times the FUSED hot-loop program (scale + logits
        + softmax + argmax + top-prob, the one a fused engine actually
        dispatches) instead of the bare logits call, so
        ``StreamEvent.device_ms`` and ``dispatch_p99_attribution`` stay
        honest when the engine serves fused.  A fresh input is placed
        per timed call: the fused program donates its input where the
        backend supports donation, and timing a donated-away buffer
        would be a use-after-free."""
        import time

        def place():
            return self._place(
                np.zeros(
                    (int(batch), self.model_window, self.model_channels),
                    np.float32,
                )
            )

        if fused:
            fn = self._fused_fn()
            params = self._params
            fn(params, place())[0].block_until_ready()  # warm
            times = []
            for _ in range(iters):
                x = place()
                t0 = time.perf_counter()
                fn(params, x)[0].block_until_ready()
                times.append((time.perf_counter() - t0) * 1e3)
        else:
            x = place()
            fn = self._inner._predict
            params = self._params
            fn(params, x).block_until_ready()  # warm
            times = []
            for _ in range(iters):
                t0 = time.perf_counter()
                fn(params, x).block_until_ready()
                times.append((time.perf_counter() - t0) * 1e3)
        return {
            "batch": int(batch),
            "iters": int(iters),
            "fused": bool(fused),
            "p50_ms": round(float(np.percentile(times, 50)), 3),
            "min_ms": round(min(times), 3),
        }

    # geometry for measure(); the engine stamps these after construction
    model_window = 200
    model_channels = 3
    # model-axis shard count: 1 everywhere except ModelParallelScorer
    model_axis_shards = 1


class ShardedScorer(DeviceScorer):
    """DeviceScorer with the batch placed sharded over a mesh.

    The input rides ``batch_sharding(mesh)`` (rows split over the data
    axes); the jitted predict specializes on the sharded layout and
    GSPMD partitions the row-independent forward across the devices —
    no collectives on this path, every device scores its own rows.
    Batches pad to ``devices × pow2`` (``serving.pad_shard``): the
    leading dim always divides the shard count and the per-device-count
    program budget stays log2-bounded.
    """

    kind = "sharded"

    def __init__(self, model, mesh):
        super().__init__(model)
        from har_tpu.parallel.mesh import data_shard_count
        from har_tpu.parallel.sharding import batch_sharding

        self.mesh = mesh
        self.devices = data_shard_count(mesh)
        # mesh.devices is the host-side device-object grid; enumerating
        # ids at construction touches no device buffer
        self.device_labels = tuple(
            str(d.id)
            for d in np.asarray(mesh.devices).flat  # harlint: host-ok
        )
        self._sharding = batch_sharding(mesh, ndim=3)

    def pad(self, windows: np.ndarray) -> np.ndarray:
        return pad_shard(windows, self.devices)

    def pad_size(self, k: int) -> int:
        per = -(-max(int(k), 1) // self.devices)
        return self.devices * (1 << (per - 1).bit_length())

    def _place(self, x: np.ndarray):
        return self._jax.device_put(x, self._sharding)


class ModelParallelScorer(ShardedScorer):
    """ShardedScorer with the PARAMS placed over the mesh's model axis.

    The 2D ``(dp, tp)`` layout: the batch rides the data axes exactly
    as in ShardedScorer (rows split ``dp``-ways, ``pad_shard`` pads per
    BATCH-shard count), while the checkpoint's ≥2-dim leaves split over
    ``tp`` in the layout the family's partition-rule table declares
    (`har_tpu.parallel.rules` — the same tables the tp trainers read).
    Placement happens ONCE, here at construction, through the
    rule-table shard-fn tree; every launch (bare, fused, calibration)
    then dispatches against the placed tree and XLA inserts the tp
    collectives the layout implies.  This is what serves a checkpoint
    too big for one device: per-device residency is the sharded leaves'
    1/tp share, reported by ``params_bytes``.

    The placement is a RUNTIME resource like the mesh itself: a journal
    recovery or an engine ``resize`` onto a model-axis mesh rebuilds
    the scorer, which re-places the params via the same rule table —
    nothing about the layout is (or needs to be) durable.
    """

    kind = "model_parallel"

    def __init__(self, model, mesh, rules=None):
        super().__init__(model, mesh)
        from har_tpu.parallel.mesh import model_shard_count
        from har_tpu.parallel.rules import (
            make_shard_fns,
            match_partition_rules,
            rules_for_params,
            shard_divisibility_check,
        )

        params = self._inner.params
        self.rules = rules_for_params(params) if rules is None else rules
        self.param_specs = match_partition_rules(self.rules, params)
        # indivisible hidden dims refuse here (ValueError), and
        # make_scorer falls back to the batch-only sharded path
        shard_divisibility_check(params, self.param_specs, mesh)
        shard_fns = make_shard_fns(mesh, self.param_specs)
        self._params = self._jax.tree.map(
            lambda place, leaf: place(leaf), shard_fns, params
        )
        self.model_axis_shards = model_shard_count(mesh)

    def params_bytes(self) -> dict:
        from jax.sharding import PartitionSpec

        from har_tpu.parallel.rules import spec_shard_count

        jax = self._jax
        is_spec = lambda s: isinstance(s, PartitionSpec)
        total = per_device = 0
        for leaf, spec in zip(
            jax.tree.leaves(self._inner.params),
            jax.tree.leaves(self.param_specs, is_leaf=is_spec),
        ):
            nbytes = int(
                np.prod(np.shape(leaf), dtype=np.int64)
                * np.dtype(leaf.dtype).itemsize
            )
            total += nbytes
            per_device += nbytes // spec_shard_count(self.mesh, spec)
        return {"total": total, "per_device": per_device}


def make_scorer(model, mesh=None, *, tier: str = "f32",
                window: int = 200, channels: int = 3, rules=None):
    """The one scorer-selection policy: a mesh with a model axis
    (``tp > 1``) gets the 2D model-parallel path (params placed once
    via the family's partition-rule table — ``rules`` overrides the
    auto-detected table), any other >1-device mesh gets the
    batch-sharded path, a jittable model gets the async single-device
    path, and everything else falls back to the synchronous HostScorer
    (which is operation-identical to the PR-2 engine).  Model swaps
    rebuild the scorer — the engine calls this again with the new
    model.

    ``tier="int8"`` serves the weight-only int8 quantization of the
    model (har_tpu.quantize.quantize_serving) behind the SAME ticket /
    fused-program interface: the int8 leaves ship to the device as
    program inputs (the artifact form — dequant is a traced op, weights
    stay int8 end-to-end) and every downstream path — pipelining,
    sharding, the fused hot loop, shadow promotion — is tier-blind.  A
    model that is already int8-backed (``Int8ServingModel``, an int8
    StableHLO export) passes through unchanged; a host-only model
    raises ValueError (there is no device program to quantize)."""
    if tier == "int8":
        from har_tpu.quantize import Int8ServingModel, quantize_serving

        if not isinstance(model, Int8ServingModel) and not bool(
            getattr(model, "int8_weights", False)
        ):
            model = quantize_serving(model)
    elif tier != "f32":
        raise ValueError(f"unknown serving tier {tier!r}")
    scorer = None
    if mesh is not None:
        from har_tpu.parallel.mesh import data_shard_count, model_shard_count

        if model_shard_count(mesh) > 1:
            try:
                scorer = ModelParallelScorer(model, mesh, rules=rules)
            except ValueError:
                # host model (no device program) or indivisible hidden
                # dims — fall through to the batch-only ladder
                scorer = None
        if scorer is None and data_shard_count(mesh) > 1:
            try:
                scorer = ShardedScorer(model, mesh)
            except ValueError:
                scorer = None  # host model: no device program to shard
    if scorer is None:
        try:
            scorer = DeviceScorer(model)
        except ValueError:
            scorer = HostScorer(model)
    scorer.model_window = int(window)
    scorer.model_channels = int(channels)
    return scorer
