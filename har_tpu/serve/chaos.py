"""Deterministic kill-point chaos harness for the fleet durability
layer — enumerate every stage boundary a process can die at, kill
there, recover, and prove the contract instead of hoping at it.

The crash model is honest about what a SIGKILL leaves behind: the
engine and the adaptation controller call ``journal.chaos_point(name)``
at each stage boundary; a ``KillPlan`` installed as the journal's chaos
hook raises ``SimulatedCrash`` at the chosen occurrence of the chosen
point, the harness abandons the server object (all process memory
gone) and calls ``FleetJournal.kill()`` — which discards the un-flushed
buffer, exactly the bytes the kernel would have lost.  Recovery then
runs the real ``FleetServer.restore`` path against whatever the
directory actually holds.

Kill points (KILL_POINTS), in pipeline order::

    post_enqueue        windows queued, push record possibly un-flushed
    pre_dispatch        queue populated, nothing scored
    mid_dispatch        batch popped from the queue, not yet launched
    mid_launch          batch launched on-device (ticket in flight),
                        nothing fetched, nothing acked
    pre_retire          ticket about to be retired: device result may
                        exist, acks not yet written
    post_score_pre_ack  scores computed, acks not yet journaled
    mid_snapshot        snapshot tmp written, rename not yet done
    mid_swap            swap applied in memory, record not yet durable
    mid_resize          elastic resize applied in memory (target_batch /
                        pipeline_depth / mesh), record not yet durable —
                        recovery serves the pre-resize capacity and the
                        controller re-issues
    mid_promote         registry promoted, fleet swap not yet applied
    mid_rollback        registry rolled back, swap-back not yet applied

The two launch/retire points exist because pipelining moved the ack
boundary: a ticket in flight at crash time is un-acked BY CONSTRUCTION,
so both points must recover exactly like pre_dispatch — the popped
windows re-derive from replayed pushes and are re-scored.  The matrix
runs at pipeline_depth 1 AND 2 in full, the ticket-centric points
additionally at ring depths 3 and 4, and the randomized property test
draws depth from {1, 2, 3, 4} (all test-pinned): depth must never
change what a crash can lose.

The verdict of every point is the same three-part contract
(test-pinned in tests/test_recovery.py, sampled by the release gate's
``recovery_smoke``):

  1. accounting — ``enqueued == scored + dropped + pending +
     lost_in_crash`` in the recovered fleet, per version and in total;
  2. zero double-scoring — no (session, t_index) event is delivered
     twice across the crash;
  3. bit-identical continuation — the union of pre-crash and
     post-recovery events equals an uninterrupted run's event stream
     exactly (decision fields), because the harness's transport
     re-delivers un-journaled samples from the recovered watermark.
"""

from __future__ import annotations

import dataclasses
import tempfile
import time

import numpy as np

from har_tpu.serve.engine import FleetConfig, FleetServer
from har_tpu.serve.faults import DispatchFaults, FakeClock
from har_tpu.serve.journal import FleetJournal, JournalConfig
from har_tpu.serve.loadgen import AnalyticDemoModel

KILL_POINTS = (
    "post_enqueue",
    "pre_dispatch",
    "mid_dispatch",
    "mid_launch",
    "pre_retire",
    "post_score_pre_ack",
    "mid_snapshot",
    "mid_swap",
    "mid_resize",
)
ENGINE_KILL_POINTS = ("mid_promote", "mid_rollback")
# the cluster control plane's migration stage boundaries
# (har_tpu.serve.cluster.controller): after a session's adopt is
# durable on the target but before the source's eviction record
# (mid_handoff — the dual-ownership window), and between per-session
# hand-offs of a failover (mid_migration — the partially-migrated
# partition).  Killed on the CLUSTER's chaos hook: the controller dies,
# the surviving worker processes do not.
CLUSTER_KILL_POINTS = ("mid_handoff", "mid_migration")
# the journal-shipping transfer's stage boundaries (shared-nothing
# failover, har_tpu.serve.net.ship): the SENDING host's agent dies
# mid-transfer (mid_ship_send — a real os._exit inside the agent
# process; the restarted agent must serve the resume from the last
# durable chunk), the RECEIVING controller dies between chunks
# (mid_ship_recv — the takeover controller resumes the staged
# transfer), and the controller dies after the verified ship lands but
# before the restored engine drains (post_ship_pre_drain — the
# takeover finds a complete staged copy and finishes).  Run in the
# wire matrix (net/chaos.py) with every worker journal in a private,
# non-shared directory.
SHIP_KILL_POINTS = (
    "mid_ship_send",
    "mid_ship_recv",
    "post_ship_pre_drain",
)
# the continuous-replication tail's stage boundaries
# (har_tpu.serve.net.tail, run by run_tail_kill_point with a warm
# standby attached to a live journaled worker): the STANDBY dies
# between chunk pulls mid-tail (mid_tail_recv — its replacement resumes
# from the durable ship.log without re-pulling one already-durable
# byte), the standby dies at the re-manifest boundary while the source
# worker snapshots/rotates under the tail (mid_tail_remanifest — the
# resumed tail adopts the new file set cleanly), and the failover
# finalizer dies after every whole-file digest verifies but before
# ship_done lands (post_tail_verify — the retried finalize re-verifies
# already-local bytes and pulls zero, over a tail the worker's death
# left PARTIAL: failover drains the missing suffix, not the journal).
TAIL_KILL_POINTS = (
    "mid_tail_recv",
    "mid_tail_remanifest",
    "post_tail_verify",
)
# the ingest gateway pair's stage boundaries (har_tpu.serve.net.gateway,
# run by run_gateway_kill_point with two elected gateways in front of
# live workers): the ACTIVE gateway dies while a push frame's header is
# being judged (mid_frame_recv — the client's frame is unacked and
# ambiguous; the re-send to the new leader dedups by watermark), it
# dies after admission said yes but before the chunks reach the workers
# (post_accept_pre_forward — admitted-but-undelivered, the worst
# ambiguity window), and it dies inside a graceful drain after marking
# itself draining but before the early lease release lands
# (mid_lease_handoff — the peer must still win by waiting out the
# un-released lease).  Every cell demands windows_lost == 0 and a
# scored event stream bit-identical to the un-killed run.
GATEWAY_KILL_POINTS = (
    "mid_frame_recv",
    "post_accept_pre_forward",
    "mid_lease_handoff",
)
# the failure modes only a REAL link has (har_tpu.serve.net.chaos —
# run over subprocess workers on loopback TCP): a slow link and a
# blackholed probe must NOT be failovers, a duplicated delivery must
# not double-score, and a split brain resolves by the `handoffs`
# generation.  Declared here beside the kill points so the full chaos
# surface reads from one module; the runners live in net/chaos.py
# (they need the transport, which imports this module).
NET_PARTITION_CASES = (
    "slow_link",
    "dropped_probe",
    "duplicate",
    "split_brain",
)

# occurrence of each point the matrix kills at by default — calibrated
# so every kill lands mid-run (some windows acked, some pending, the
# swap schedule still ahead or just behind; for the cluster points,
# at least one session already handed off when the controller dies)
_DEFAULT_AT = {
    "post_enqueue": 12,
    "pre_dispatch": 3,
    "mid_dispatch": 2,
    "mid_launch": 2,
    "pre_retire": 2,
    "post_score_pre_ack": 2,
    "mid_snapshot": 1,
    "mid_swap": 1,
    "mid_resize": 1,
    "mid_handoff": 1,
    "mid_migration": 2,
    # ship-axis occurrences: the chunk counts are calibrated against
    # the matrix's small ship_chunk_bytes so both kills land genuinely
    # MID-transfer (durable progress exists, the transfer is unfinished)
    "mid_ship_send": 3,
    "mid_ship_recv": 3,
    "post_ship_pre_drain": 1,
    # tail-axis occurrences: the second chunk pull of a cycle (durable
    # progress exists, the pass is unfinished), the first re-manifest
    # boundary, and the first finalize verify window
    "mid_tail_recv": 2,
    "mid_tail_remanifest": 1,
    "post_tail_verify": 1,
    # gateway-axis occurrences: a mid-run frame receipt (rounds already
    # delivered, more coming), the second admitted-but-unforwarded
    # window, and the first drain hand-off
    "mid_frame_recv": 3,
    "post_accept_pre_forward": 2,
    "mid_lease_handoff": 1,
}


class SimulatedCrash(Exception):
    """Raised by a KillPlan at its chosen stage boundary."""


@dataclasses.dataclass
class KillPlan:
    """Journal chaos hook: crash at the ``at``-th hit of ``point``."""

    point: str
    at: int = 1
    hits: int = 0
    fired: bool = False

    def __call__(self, point: str) -> None:
        if point != self.point:
            return
        self.hits += 1
        if self.hits == self.at:
            self.fired = True
            raise SimulatedCrash(self.point)


def _recordings(n_sessions: int, n_samples: int, channels: int, seed: int):
    rng = np.random.default_rng((seed, 0xC4A5))
    return [
        rng.normal(size=(n_samples, channels)).astype(np.float32)
        for _ in range(n_sessions)
    ]


def _event_key(fe):
    return (fe.session_id, fe.event.t_index)


def _event_fields(fe):
    ev = fe.event
    return (
        ev.t_index, ev.label, ev.raw_label, ev.drift,
        ev.probability.tobytes(),
    )


def _deliver(server, recordings, cursors, upto, hop, clock, events,
             on_round=None):
    """Round-robin hop-aligned delivery until every cursor reaches
    min(upto, len(recording)); force-poll after each round.  Resuming
    from arbitrary per-session watermarks re-aligns to the hop grid, so
    an interrupted schedule continues exactly where it died.
    ``on_round()`` fires after each round's poll — the tail kill cells
    interleave standby cycles there."""
    while True:
        active = False
        for i, rec in enumerate(recordings):
            stop = min(upto, len(rec))
            if cursors[i] >= stop:
                continue
            active = True
            take = hop - (cursors[i] % hop) or hop
            chunk = rec[cursors[i] : min(cursors[i] + take, stop)]
            cursors[i] += len(chunk)
            server.push(i, chunk)
        if not active:
            break
        events.extend(server.poll(force=True))
        clock.advance(0.01)
        if on_round is not None:
            on_round()
    events.extend(server.flush())


def _run_schedule(server, recordings, cursors, *, hop, clock, models,
                  swap_sample, events):
    """The one delivery schedule both the reference and the crashed+
    recovered runs execute: everything up to ``swap_sample`` scores on
    model A, then the swap, then the rest — driven purely off cursor
    state so it resumes deterministically from recovered watermarks.
    ``events`` is caller-owned so delivered events survive a
    SimulatedCrash raised mid-schedule."""
    _deliver(server, recordings, cursors, swap_sample, hop, clock, events)
    if server.model_version == "A":
        # elastic resize at the same schedule point the swap fires:
        # gives mid_resize a boundary to kill at, and proves depth/
        # batch changes never move an event (scores are row-independent
        # and retire order is FIFO, so the reference run — which
        # resizes identically — stays bit-identical).  Guarded like the
        # cluster path so a crash-resume re-issue is a true no-op: a
        # recovered server already at 48 must not journal a second
        # resize record or double-count stats.resizes.
        if server.config.target_batch != 48:
            server.resize(target_batch=48)
        server.swap_model(models["B"], version="B")
    _deliver(
        server, recordings, cursors, max(map(len, recordings)), hop,
        clock, events,
    )
    return events


def run_kill_point(
    point: str,
    *,
    at: int | None = None,
    sessions: int = 8,
    seed: int = 0,
    n_samples: int = 600,
    window: int = 100,
    hop: int = 50,
    flush_every: int = 8,
    snapshot_every: int = 40,
    fsync: bool = True,
    journal_dir: str | None = None,
    pipeline_depth: int = 1,
    mesh=None,
) -> dict:
    """Kill a journaled fleet at one stage boundary, recover, resume,
    and return the verdict dict (``ok`` + evidence).

    ``mesh`` runs the whole matrix behind a mesh-backed dispatch plane
    (a 2D ``(dp, tp)`` mesh serves through ``ModelParallelScorer``,
    params placed via the family rule table): the A/B models become
    jitted demo models (the analytic pair has no device program), and
    recovery re-places the params through the SAME table — placement is
    a runtime resource like the mesh, never journaled.

    Runs under the PR-2 FakeClock + DispatchFaults harness (periodic
    injected stalls on the fake clock: the fault plumbing is live, the
    scores stay deterministic), with a mid-run hot swap in the schedule
    so swap-adjacent kill points have something to interrupt.

    ``pipeline_depth > 1`` runs the same matrix with tickets genuinely
    in flight at the kill instant — the conservation law and the
    bit-identical-continuation contract must hold unchanged, because a
    ticket in flight is un-acked by construction.
    """
    if point in ENGINE_KILL_POINTS:
        return run_engine_kill_point(
            point, sessions=sessions, seed=seed, journal_dir=journal_dir,
            pipeline_depth=pipeline_depth, mesh=mesh,
        )
    if point not in KILL_POINTS:
        raise ValueError(f"unknown kill point {point!r}")
    at = _DEFAULT_AT[point] if at is None else at
    recordings = _recordings(sessions, n_samples, 3, seed)
    if mesh is None:
        models = {
            "A": AnalyticDemoModel(), "B": AnalyticDemoModel(tau=5.0),
        }
    else:
        # mesh-backed dispatch plane: the analytic pair is host-only,
        # so the A/B swap serves two jitted demo checkpoints instead
        from har_tpu.serve.loadgen import JitDemoModel

        models = {
            "A": JitDemoModel(window=window, channels=3, seed=1729),
            "B": JitDemoModel(window=window, channels=3, seed=5),
        }
    swap_sample = (n_samples // hop // 2) * hop  # mid-recording
    config = FleetConfig(
        max_sessions=sessions, target_batch=32, max_delay_ms=0.0,
        retries=1, pipeline_depth=pipeline_depth,
    )

    def build(clock, journal):
        server = FleetServer(
            models["A"], window=window, hop=hop, channels=3,
            smoothing="ema", config=config,
            fault_hook=DispatchFaults(
                stall_every=3, stall_ms=1.0, fake_clock=clock
            ),
            clock=clock, model_version="A", journal=journal,
            mesh=mesh,
        )
        for i in range(sessions):
            server.add_session(i)
        return server

    # ---- reference: the uninterrupted run --------------------------------
    ref_clock = FakeClock()
    ref_server = build(ref_clock, None)
    ref_events: list = []
    _run_schedule(
        ref_server, recordings, [0] * sessions, hop=hop, clock=ref_clock,
        models=models, swap_sample=swap_sample, events=ref_events,
    )

    # ---- crashed run -----------------------------------------------------
    tmp = None
    if journal_dir is None:
        tmp = journal_dir = tempfile.mkdtemp(prefix="har_chaos_")
    try:
        journal = FleetJournal(
            journal_dir,
            JournalConfig(
                flush_every=flush_every, snapshot_every=snapshot_every,
                fsync=fsync,
            ),
        )
        clock = FakeClock()
        server = build(clock, journal)
        # armed only after construction: the attach-time snapshot is
        # part of setup, not of the schedule under chaos
        plan = KillPlan(point, at)
        journal.chaos = plan
        pre_events: list = []
        cursors = [0] * sessions
        try:
            _run_schedule(
                server, recordings, cursors, hop=hop, clock=clock,
                models=models, swap_sample=swap_sample, events=pre_events,
            )
            journal.close()
            return {
                "ok": False, "point": point,
                "why": f"kill point {point!r} never fired (at={at})",
                "windows_lost": 0, "recovery_ms": 0.0,
            }
        except SimulatedCrash:
            # SIGKILL: process memory gone, un-flushed journal bytes
            # gone; only `pre_events` (already delivered to the
            # consumer before the crash) and the disk survive
            journal.kill()

        # ---- recovery ----------------------------------------------------
        t0 = time.perf_counter()
        clock2 = FakeClock(clock.t)
        restored = FleetServer.restore(
            journal_dir,
            lambda ver: models[ver],
            clock=clock2,
            fault_hook=DispatchFaults(
                stall_every=3, stall_ms=1.0, fake_clock=clock2
            ),
            mesh=mesh,
        )
        recovery_ms = (time.perf_counter() - t0) * 1e3

        # ---- resume: transport re-delivers from the watermark ------------
        post_events: list = []
        post_events.extend(restored.poll(force=True))  # drain recovered
        resume_cursors = [restored.watermark(i) for i in range(sessions)]
        _run_schedule(
            restored, recordings, resume_cursors, hop=hop,
            clock=clock2, models=models, swap_sample=swap_sample,
            events=post_events,
        )

        # ---- verdict -----------------------------------------------------
        return _verdict(
            point, ref_events, pre_events, post_events, restored,
            recovery_ms,
        )
    finally:
        if tmp is not None:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)


def _verdict(point, ref_events, pre_events, post_events, restored,
             recovery_ms) -> dict:
    why = None
    combined = list(pre_events) + list(post_events)
    keys = [_event_key(e) for e in combined]
    if len(keys) != len(set(keys)):
        why = "an event was delivered twice across the crash"
    by_sid: dict = {}
    for e in combined:
        by_sid.setdefault(e.session_id, []).append(e)
    ref_by_sid: dict = {}
    for e in ref_events:
        ref_by_sid.setdefault(e.session_id, []).append(e)
    windows_lost = sum(len(v) for v in ref_by_sid.values()) - sum(
        len(v) for v in by_sid.values()
    )
    if why is None and windows_lost != 0:
        why = f"{windows_lost} window(s) lost vs the uninterrupted run"
    if why is None:
        for sid, want in ref_by_sid.items():
            got = by_sid.get(sid, [])
            if [_event_fields(e) for e in got] != [
                _event_fields(e) for e in want
            ]:
                why = (
                    f"session {sid!r} events diverge from the "
                    "uninterrupted run"
                )
                break
    acct = restored.stats.accounting()
    if why is None and not (
        acct["balanced"] and acct["pending"] == 0 and acct["dropped"] == 0
    ):
        why = f"accounting violated after recovery: {acct}"
    if why is None and restored.stats.recoveries != 1:
        why = f"recoveries counter is {restored.stats.recoveries}, not 1"
    return {
        "ok": why is None,
        "point": point,
        "why": why,
        "windows_lost": max(windows_lost, 0),
        "delivered_pre_crash": len(pre_events),
        "delivered_post_recovery": len(post_events),
        "recovery_ms": round(recovery_ms, 3),
        "accounting": acct,
    }


def run_random_kill(seed: int, mesh=None) -> dict:
    """Seed-randomized kill-point draw for the property test: point,
    occurrence, flush batching, snapshot cadence AND pipeline depth all
    vary — the recovery contract must hold for every combination.  The
    depth draw spans the full ticket ring {1, 2, 3, 4}: at depth >= 3
    several tickets are genuinely in flight at the kill instant, and
    every one of them must recover as ordinary un-acked pending.
    ``mesh`` runs the draw behind a mesh-backed dispatch plane (see
    `run_kill_point`)."""
    rng = np.random.default_rng((seed, 0xDEAD))
    point = KILL_POINTS[int(rng.integers(len(KILL_POINTS)))]
    at = _DEFAULT_AT[point] + int(rng.integers(0, 3))
    out = run_kill_point(
        point,
        at=at,
        sessions=int(rng.integers(3, 9)),
        seed=seed,
        flush_every=int(rng.choice([1, 4, 16, 64])),
        snapshot_every=int(rng.choice([0, 10, 30])),
        pipeline_depth=int(rng.choice([1, 2, 3, 4])),
        mesh=mesh,
    )
    out["seed"] = seed
    if not out["ok"] and "never fired" in (out["why"] or ""):
        # a tiny random fleet may finish before a late occurrence; that
        # is a harness-calibration miss, not a durability failure —
        # retry at the first occurrence so every seed tests recovery
        out = run_kill_point(point, at=1, sessions=4, seed=seed, mesh=mesh)
        out["seed"] = seed
    return out


def run_engine_kill_point(
    point: str, *, sessions: int = 8, seed: int = 0,
    journal_dir: str | None = None, pipeline_depth: int = 1,
    mesh=None,
) -> dict:
    """Kill inside the adaptation controller's registry transitions —
    after ``registry.promote`` but before the fleet swap applies
    (``mid_promote``), or after ``registry.rollback`` but before the
    swap-back (``mid_rollback``) — then recover and prove the
    half-finished transition completes cleanly: the recovered fleet
    serves exactly the registry's CURRENT version, with accounting
    intact.  ``mesh`` runs the transition behind a mesh-backed
    dispatch plane, as in `run_kill_point`."""
    import shutil

    from har_tpu.adapt.registry import ModelRegistry
    from har_tpu.adapt.shadow import ShadowConfig
    from har_tpu.adapt.swap import AdaptationConfig, AdaptationEngine
    from har_tpu.adapt.trigger import TriggerConfig
    from har_tpu.monitoring import DriftMonitor

    if point not in ENGINE_KILL_POINTS:
        raise ValueError(f"unknown engine kill point {point!r}")
    tmp = None
    if journal_dir is None:
        tmp = journal_dir = tempfile.mkdtemp(prefix="har_chaos_adapt_")
    reg_root = journal_dir + ".registry"
    try:
        clock = FakeClock()
        journal = FleetJournal(
            journal_dir, JournalConfig(flush_every=8, snapshot_every=0)
        )
        if mesh is None:
            incumbent = AnalyticDemoModel()
            candidate = AnalyticDemoModel(tau=5.0)
        else:
            from har_tpu.serve.loadgen import JitDemoModel

            incumbent = JitDemoModel(window=100, channels=3, seed=1729)
            candidate = JitDemoModel(window=100, channels=3, seed=5)
        models: dict = {}

        # post-swap dispatch failures force the probation regression
        # that reaches the rollback path
        faults = DispatchFaults(fake_clock=clock)
        server = FleetServer(
            incumbent, window=100, hop=100, channels=3, smoothing="none",
            config=FleetConfig(
                max_sessions=sessions, max_delay_ms=0.0, retries=0,
                pipeline_depth=pipeline_depth,
            ),
            clock=clock, fault_hook=faults, journal=journal,
            mesh=mesh,
        )
        rng = np.random.default_rng((seed, 77))
        recs = [
            rng.normal(size=(1200, 3)).astype(np.float32)
            for _ in range(sessions)
        ]
        for i in range(sessions):
            server.add_session(
                i,
                monitor=DriftMonitor(
                    np.zeros(3), np.ones(3), halflife=50.0, patience=2
                ),
            )
        registry = ModelRegistry(reg_root, clock=clock)
        engine = AdaptationEngine(
            server, registry, lambda job: candidate,
            config=AdaptationConfig(
                probation_dispatches=3, max_shadow_dispatches=8
            ),
            trigger_config=TriggerConfig(
                min_sessions=2, window_s=1e9, cooldown_s=1e9,
                recovery_patience=1,
            ),
            # mesh-backed pairs are independently-seeded jit models, so
            # the argmax-agreement gate is off: the matrix tests the
            # journaled transition, not candidate quality
            shadow_config=ShadowConfig(
                sample_every=1, min_windows=4,
                min_agreement=0.98 if mesh is None else 0.0,
            ),
            clock=clock,
        )
        models[server.model_version] = incumbent
        # armed only after setup (attach snapshot + bootstrap register)
        plan = KillPlan(point, 1)
        journal.chaos = plan

        def loader(ver: str):
            if ver not in models:
                # the candidate registers as the next version id
                models[ver] = candidate
            return models[ver]

        crashed = False
        try:
            for rnd in range(10):
                for i in range(sessions):
                    chunk = recs[i][rnd * 100 : (rnd + 1) * 100]
                    if i < sessions // 2 and rnd >= 1:
                        chunk = chunk + 25.0  # population re-mount
                    server.push(i, chunk)
                server.poll(force=True)
                if (
                    point == "mid_rollback"
                    and engine.state == "probation"
                ):
                    faults.fail_every = 1  # regression: every dispatch dies
                engine.step()
                clock.advance(1.0)
        except SimulatedCrash:
            crashed = True
            journal.kill()
        if not crashed:
            journal.close()
            shutil.rmtree(reg_root, ignore_errors=True)
            return {
                "ok": False, "point": point,
                "why": f"kill point {point!r} never fired",
                "windows_lost": 0, "recovery_ms": 0.0,
            }

        # ---- recovery ----------------------------------------------------
        t0 = time.perf_counter()
        clock2 = FakeClock(clock.t)
        restored = FleetServer.restore(
            journal_dir, loader, clock=clock2, mesh=mesh
        )
        registry2 = ModelRegistry(reg_root, clock=clock2)
        engine2 = AdaptationEngine(
            restored, registry2, lambda job: candidate,
            config=AdaptationConfig(
                probation_dispatches=3, max_shadow_dispatches=8
            ),
            trigger_config=TriggerConfig(
                min_sessions=2, window_s=1e9, cooldown_s=1e9,
                recovery_patience=1,
            ),
            shadow_config=ShadowConfig(
                sample_every=1, min_windows=4,
                min_agreement=0.98 if mesh is None else 0.0,
            ),
            clock=clock2,
            resume=True,
            loader=loader,
        )
        recovery_ms = (time.perf_counter() - t0) * 1e3

        # resume a few clean rounds (faults off: probation must close)
        restored.poll(force=True)
        cursors = [restored.watermark(i) for i in range(sessions)]
        for rnd in range(3):
            for i in range(sessions):
                chunk = recs[i][cursors[i] : cursors[i] + 100]
                cursors[i] += 100
                if len(chunk):
                    restored.push(i, chunk)
            restored.poll(force=True)
            engine2.step()
            clock2.advance(1.0)
        restored.flush()
        engine2.step()

        acct = restored.stats.accounting()
        cur = registry2.current()
        why = None
        if cur is None or cur.name != restored.model_version:
            why = (
                f"registry CURRENT ({None if cur is None else cur.name}) "
                f"!= serving version ({restored.model_version}) after "
                "recovery"
            )
        elif not acct["balanced"] or acct["pending"] != 0:
            why = f"accounting violated after recovery: {acct}"
        elif point == "mid_promote" and cur.version < 2:
            why = "mid_promote recovery did not complete the promotion"
        elif point == "mid_rollback" and cur.version != 1:
            why = "mid_rollback recovery did not land on the incumbent"
        elif engine2.state not in ("serving",):
            why = f"engine did not settle post-recovery: {engine2.state}"
        return {
            "ok": why is None,
            "point": point,
            "why": why,
            "windows_lost": 0,
            "recovery_ms": round(recovery_ms, 3),
            "serving_version": restored.model_version,
            "registry_current": cur.name if cur else None,
            "accounting": acct,
        }
    finally:
        shutil.rmtree(reg_root, ignore_errors=True)
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------
# replication-axis chaos: a warm standby tail-follows a live journaled
# worker; the standby dies mid-tail / at the re-manifest boundary, or
# the finalize verifier dies over a partial tail — then the worker is
# killed and recovery runs from the STANDBY's staging directory.


def run_tail_kill_point(
    point: str,
    *,
    at: int | None = None,
    sessions: int = 6,
    seed: int = 0,
    n_samples: int = 600,
    window: int = 100,
    hop: int = 50,
    flush_every: int = 8,
    chunk_bytes: int = 1024,
) -> dict:
    """Kill the continuous-replication tail at one of its stage
    boundaries (TAIL_KILL_POINTS), resume it, kill the SOURCE worker,
    fail over from the standby's staging directory, and demand the
    same three-part contract as every other cell — plus the
    replication-specific evidence:

    - ``mid_tail_recv``: the replacement standby resumes from the
      durable ship.log; total bytes pulled across BOTH standby
      incarnations equals the source manifest exactly (zero re-pulled
      bytes), and the caught-up failover transfers zero.
    - ``mid_tail_remanifest``: the source rotates/snapshots under the
      tail; the resumed tail adopts the new file set (a durable
      ``ship_remanifest`` record lands, the warm replica re-founds on
      the new snapshot) and the caught-up failover transfers zero.
    - ``post_tail_verify``: the tail is deliberately left PARTIAL
      (cycles stop early), the first finalize pulls the missing
      suffix then dies after verification but before ``ship.done``;
      the retried finalize re-verifies already-local bytes and pulls
      zero.

    The source worker runs the ordinary journaled schedule; its death
    is ``journal.kill()`` after phase A (pending windows exist, the
    swap still ahead) — the tail axis is about the STANDBY dying, not
    the worker, so the worker's own kill points stay in KILL_POINTS.
    """
    import shutil

    from har_tpu.serve.journal import read_segment_from
    from har_tpu.serve.net.ship import journal_manifest
    from har_tpu.serve.net.tail import LocalShipSource
    from har_tpu.serve.replica import StandbyAgent

    if point not in TAIL_KILL_POINTS:
        raise ValueError(f"unknown tail kill point {point!r}")
    at = _DEFAULT_AT[point] if at is None else at
    # mid_tail_remanifest needs the source to rotate under the tail;
    # the other two pin append-only byte accounting, which wants a
    # stable file set (no prunes) — attach-time snapshot only
    snapshot_every = 40 if point == "mid_tail_remanifest" else 0
    recordings = _recordings(sessions, n_samples, 3, seed)
    models = {"A": AnalyticDemoModel(), "B": AnalyticDemoModel(tau=5.0)}
    loader = lambda ver: models[ver]  # noqa: E731
    swap_sample = (n_samples // hop // 2) * hop
    config = FleetConfig(
        max_sessions=sessions, target_batch=32, max_delay_ms=0.0,
        retries=1,
    )

    def build(clock, journal):
        server = FleetServer(
            models["A"], window=window, hop=hop, channels=3,
            smoothing="ema", config=config,
            fault_hook=DispatchFaults(
                stall_every=3, stall_ms=1.0, fake_clock=clock
            ),
            clock=clock, model_version="A", journal=journal,
        )
        for i in range(sessions):
            server.add_session(i)
        return server

    # ---- reference: the uninterrupted run --------------------------------
    ref_clock = FakeClock()
    ref_server = build(ref_clock, None)
    ref_events: list = []
    _run_schedule(
        ref_server, recordings, [0] * sessions, hop=hop, clock=ref_clock,
        models=models, swap_sample=swap_sample, events=ref_events,
    )

    td = tempfile.mkdtemp(prefix="har_chaos_tail_")
    try:
        src_root = f"{td}/src"
        src_home = f"{src_root}/w0"
        sb_root = f"{td}/sb"
        journal = FleetJournal(
            src_home,
            JournalConfig(
                flush_every=flush_every, snapshot_every=snapshot_every
            ),
        )
        clock = FakeClock()
        server = build(clock, journal)
        plan = KillPlan(point, at)
        standbys = [
            StandbyAgent(
                sb_root, {"w0": LocalShipSource(src_root)}, loader=loader,
                chunk_bytes=chunk_bytes, chaos=plan, clock=clock,
            )
        ]

        def cycle_once():
            """One standby cycle; a SimulatedCrash is the standby
            process dying — a REPLACEMENT agent (fresh memory, no
            chaos) resumes over the same staging root from the
            durable ship.log."""
            try:
                standbys[-1].cycle()
            except SimulatedCrash:
                standbys.append(
                    StandbyAgent(
                        sb_root, {"w0": LocalShipSource(src_root)},
                        loader=loader, chunk_bytes=chunk_bytes,
                        clock=clock,
                    )
                )

        rounds = {"n": 0}

        def on_round():
            rounds["n"] += 1
            if point == "post_tail_verify" and rounds["n"] > 3:
                return  # stop tailing early: the tail stays PARTIAL
            cycle_once()

        # ---- phase A: live worker under tail, then SIGKILL it ------------
        pre_events: list = []
        cursors = [0] * sessions
        _deliver(
            server, recordings, cursors, swap_sample, hop, clock,
            pre_events, on_round=on_round,
        )
        journal.kill()

        # ---- catch-up: the journal is static now; drain the tail ---------
        if point != "post_tail_verify":
            for _ in range(3):
                cycle_once()
            if not plan.fired:
                shutil.rmtree(td, ignore_errors=True)
                return {
                    "ok": False, "point": point,
                    "why": f"kill point {point!r} never fired (at={at})",
                    "windows_lost": 0, "recovery_ms": 0.0,
                }

        # ---- failover: finalize from the standby's bytes -----------------
        sb = standbys[-1]
        pre_shipped = sb.stats.shipped_bytes
        t0 = time.perf_counter()
        finalize_crashed = False
        first_bytes = 0
        try:
            fin = sb.finalize("w0")
        except SimulatedCrash:
            finalize_crashed = True
            first_bytes = sb.stats.shipped_bytes - pre_shipped
            fin = sb.finalize("w0")  # retried over already-local bytes
        failover_path_bytes = first_bytes + fin["bytes"]

        why = None
        if point == "post_tail_verify":
            if not finalize_crashed:
                why = f"kill point {point!r} never fired (at={at})"
            elif first_bytes <= 0:
                why = (
                    "the partial tail's finalize pulled no missing "
                    "suffix — the cell did not exercise the drain"
                )
            elif fin["bytes"] != 0:
                why = (
                    "retried finalize re-pulled "
                    f"{fin['bytes']} byte(s); the verify must be "
                    "idempotent over already-local bytes"
                )
        else:
            if fin["bytes"] != 0:
                why = (
                    f"caught-up failover transferred {fin['bytes']} "
                    "byte(s); a fully-tailed standby must transfer zero"
                )
        if why is None and point == "mid_tail_recv":
            # zero re-pulled bytes: every standby incarnation's pulls,
            # summed, equal the final source manifest exactly (valid
            # because snapshot_every=0 means no file was ever pruned)
            total = sum(
                e["size"] for e in journal_manifest(src_home)
            )
            pulled = sum(s.stats.shipped_bytes for s in standbys)
            if pulled != total:
                why = (
                    f"pulled {pulled} byte(s) across standby "
                    f"incarnations for a {total}-byte manifest — the "
                    "resume re-pulled already-durable bytes"
                )
        remanifests = 0
        if why is None and point == "mid_tail_remanifest":
            ship_log = f"{sb.dest('w0')}/ship.log"
            records, _ = read_segment_from(ship_log, 0)
            remanifests = sum(
                1 for meta, _p in records
                if meta.get("t") == "ship_remanifest"
            )
            replica = sb.replicas.get("w0")
            if remanifests < 1:
                why = (
                    "no durable ship_remanifest record: the tail never "
                    "crossed the rotation boundary"
                )
            elif replica is None or replica.rebuilds < 1:
                why = (
                    "the warm replica never re-founded on the rotated "
                    "snapshot"
                )
        if why is not None:
            shutil.rmtree(td, ignore_errors=True)
            return {
                "ok": False, "point": point, "why": why,
                "windows_lost": 0, "recovery_ms": 0.0,
                "failover_path_bytes": failover_path_bytes,
            }

        # ---- recovery from the STANDBY's staging directory ---------------
        clock2 = FakeClock(clock.t)
        restored = FleetServer.restore(
            sb.dest("w0"),
            loader,
            clock=clock2,
            fault_hook=DispatchFaults(
                stall_every=3, stall_ms=1.0, fake_clock=clock2
            ),
        )
        recovery_ms = (time.perf_counter() - t0) * 1e3

        post_events: list = []
        post_events.extend(restored.poll(force=True))
        resume_cursors = [restored.watermark(i) for i in range(sessions)]
        _run_schedule(
            restored, recordings, resume_cursors, hop=hop,
            clock=clock2, models=models, swap_sample=swap_sample,
            events=post_events,
        )

        out = _verdict(
            point, ref_events, pre_events, post_events, restored,
            recovery_ms,
        )
        out.update(
            failover_path_bytes=failover_path_bytes,
            standby_incarnations=len(standbys),
            finalize_resumes=fin["resumes"],
            remanifests=remanifests,
            tail_cycles=sum(s.cycles for s in standbys),
        )
        return out
    finally:
        shutil.rmtree(td, ignore_errors=True)


# ---------------------------------------------------------------------
# worker-axis chaos: kill one worker of a running cluster
# (har_tpu.serve.cluster) and demand the same three-part contract
# ACROSS the failover — plus the two control-plane kill points.


def _build_cluster(root, clock, *, sessions, workers, window, hop,
                   model, flush_every, snapshot_every, loader):
    from har_tpu.serve.cluster.controller import (
        ClusterConfig,
        FleetCluster,
    )

    return FleetCluster(
        model,
        root,
        workers=workers,
        window=window,
        hop=hop,
        channels=3,
        smoothing="ema",
        fleet_config=FleetConfig(
            max_sessions=sessions, target_batch=32, max_delay_ms=0.0,
            retries=1,
        ),
        # flush_every must exceed the per-poll ack volume: an ack that
        # auto-flushes mid-poll would be durable-but-undelivered if the
        # kill lands before the poll returns — a loss channel the
        # single-server matrix calibrates around and the cluster
        # harness excludes by construction
        journal_config=JournalConfig(
            flush_every=flush_every, snapshot_every=snapshot_every
        ),
        config=ClusterConfig(
            lease_s=0.2, probe_retries=3, probe_base_ms=20.0,
            probe_cap_ms=100.0,
        ),
        clock=clock,
        loader=loader,
        fault_hook_for=lambda wid: DispatchFaults(
            stall_every=3, stall_ms=1.0, fake_clock=clock
        ),
    )




def _drive_cluster(cluster, recordings, cursors, upto, hop, clock,
                   events, on_round=None, max_rounds=20000):
    """Hop-aligned round-robin delivery against a cluster, failover-
    aware: a push to an unreachable worker keeps its cursor (the
    transport re-delivers), every completed migration rewinds its
    session's cursor to the adopted watermark, and the loop keeps
    polling past the end of delivery until no session is stranded on a
    dead worker — the failure detector needs polls and clock to run.
    ``on_round(cluster)`` fires after every poll (kill scheduling and
    the every-snapshot conservation log live there)."""
    from har_tpu.serve.cluster.membership import WorkerUnavailable

    # entry rewind: a takeover/migration before this drive moved
    # sessions; their durable watermark is where delivery resumes
    for i in range(len(recordings)):
        try:
            cursors[i] = cluster.watermark(i)
        except WorkerUnavailable:
            pass  # mid-failover: the migration-log rewind below lands
    seen_migrations = len(cluster.migration_log)
    for _ in range(max_rounds):
        active = False
        for i, rec in enumerate(recordings):
            stop = min(upto, len(rec))
            if cursors[i] >= stop:
                continue
            active = True
            take = hop - (cursors[i] % hop) or hop
            chunk = rec[cursors[i] : min(cursors[i] + take, stop)]
            try:
                cluster.push(i, chunk)
            except WorkerUnavailable:
                continue  # cursor kept; re-delivered post-failover
            cursors[i] += len(chunk)
        events.extend(cluster.poll(force=True))
        clock.advance(0.05)
        if on_round is not None:
            on_round(cluster)
        while seen_migrations < len(cluster.migration_log):
            sid = cluster.migration_log[seen_migrations]["sid"]
            cursors[sid] = cluster.watermark(sid)
            seen_migrations += 1
        if not active:
            stranded = any(
                cluster._workers.get(cluster.worker_of(i)) is None
                or not cluster._workers[cluster.worker_of(i)].alive
                for i in range(len(recordings))
            )
            # the migration rewind above may have re-opened cursors:
            # this phase must finish its own re-delivery BEFORE
            # returning (the schedule's next step may be a model swap
            # — delivering phase-1 windows after it would score them
            # on the wrong model and break bit-identity)
            rewound = any(
                cursors[i] < min(upto, len(recordings[i]))
                for i in range(len(recordings))
            )
            if not stranded and not rewound:
                break
    else:  # pragma: no cover - harness guard
        raise RuntimeError("cluster drive did not converge")
    events.extend(cluster.flush())
    if on_round is not None:
        on_round(cluster)


def _cluster_schedule(cluster, recordings, cursors, *, hop, clock,
                      models, swap_sample, events, on_round=None):
    """The one delivery schedule reference and crashed cluster runs
    share: deliver to ``swap_sample``, broadcast the hot swap (per-
    worker idempotent — a resumed schedule re-issues it only where it
    has not landed), deliver the rest.  Driven purely off cursor state,
    so it resumes deterministically after a kill."""
    _drive_cluster(
        cluster, recordings, cursors, swap_sample, hop, clock, events,
        on_round,
    )
    # per-worker elastic resize at the swap point — the cluster-side
    # boundary mid_resize kills at.  Guarded per worker exactly like
    # the idempotent swap broadcast: a resumed schedule re-issues it
    # only where it has not landed.
    for w in cluster._workers.values():
        if w.alive and w.server.config.target_batch != 48:
            w.server.resize(target_batch=48)
    cluster.swap_model(models["B"], version="B")
    _drive_cluster(
        cluster, recordings, cursors, max(map(len, recordings)), hop,
        clock, events, on_round,
    )


def run_cluster_kill_point(
    point: str,
    *,
    at: int | None = None,
    workers: int = 3,
    sessions: int = 12,
    seed: int = 0,
    n_samples: int = 300,
    window: int = 100,
    hop: int = 50,
    flush_every: int = 512,
    snapshot_every: int = 40,
    kill_round: int = 3,
    standby: bool = False,
) -> dict:
    """Kill one worker of an N-worker cluster at a stage boundary (any
    of the engine KILL_POINTS, fired inside the victim's own journal
    hook) or kill the CONTROLLER inside the migration machinery
    (CLUSTER_KILL_POINTS), then let failover / takeover finish the job
    and demand the cross-worker contract:

      1. zero double-scored — no (session, t_index) event delivered
         twice across the kill, no matter which worker scored it;
      2. migrated streams bit-identical — every session's combined
         event stream equals the un-killed cluster run's, decision
         fields exact;
      3. global conservation — ``enqueued == scored + dropped +
         pending + lost_in_crash`` summed over live workers + the
         retired ledger, balanced in EVERY accounting snapshot and
         drained to pending 0 at the end, with zero windows lost (the
         transport re-delivers from the adopted watermarks).

    Worker-axis kills leave the controller alive (failover path);
    cluster-point kills model a controller loss mid-migration — the
    worker processes survive and ``FleetCluster.takeover`` adopts
    them, completing the orphaned failover idempotently.

    ``standby=True`` runs the SAME matrix with a warm standby
    registered on the crashed cluster (the reference run never has
    one — a standby must not change one delivered byte): the standby
    tail-follows every worker from the controller's poll loop, and the
    verdict additionally demands that the failover sourced the
    partition from the standby (``standby_fetches >= 1``) over a
    zero-byte failover path (``failover_path_bytes == 0`` — the tail
    was caught up, so finalize moved nothing).
    """
    import os
    import shutil

    if point not in KILL_POINTS and point not in CLUSTER_KILL_POINTS:
        raise ValueError(f"unknown cluster kill point {point!r}")
    at = _DEFAULT_AT[point] if at is None else at
    recordings = _recordings(sessions, n_samples, 3, seed)
    models = {"A": AnalyticDemoModel(), "B": AnalyticDemoModel(tau=5.0)}

    def loader(ver):
        return models.get(ver, models["A"])

    swap_sample = (n_samples // hop // 2) * hop
    build_kwargs = dict(
        sessions=sessions, workers=workers, window=window, hop=hop,
        flush_every=flush_every, snapshot_every=snapshot_every,
        loader=loader,
    )

    # ---- reference: the un-killed cluster run -----------------------
    ref_root = tempfile.mkdtemp(prefix="har_cluster_ref_")
    try:
        ref_clock = FakeClock()
        ref = _build_cluster(
            ref_root, ref_clock, model=models["A"], **build_kwargs
        )
        for i in range(sessions):
            ref.add_session(i)
        ref_events: list = []
        _cluster_schedule(
            ref, recordings, [0] * sessions, hop=hop, clock=ref_clock,
            models=models, swap_sample=swap_sample, events=ref_events,
        )
        ref.close()
    finally:
        shutil.rmtree(ref_root, ignore_errors=True)

    # ---- crashed run ------------------------------------------------
    root = tempfile.mkdtemp(prefix="har_cluster_chaos_")
    try:
        clock = FakeClock()
        cluster = _build_cluster(
            root, clock, model=models["A"], **build_kwargs
        )
        for i in range(sessions):
            cluster.add_session(i)
        if standby:
            from har_tpu.serve.net.tail import LocalShipSource
            from har_tpu.serve.replica import StandbyAgent

            cluster.register_standby(
                StandbyAgent(
                    os.path.join(root, "_replica"),
                    {
                        wid: LocalShipSource(root)
                        for wid in cluster._workers
                    },
                    loader=loader,
                )
            )
        victim = cluster.worker_of(0)
        plan = KillPlan(point, at)
        if point in CLUSTER_KILL_POINTS:
            # controller kill mid-migration: the victim worker is
            # SIGKILLed outright partway through delivery; the plan
            # then fires inside the resulting failover's hand-offs
            cluster.chaos = plan
        else:
            cluster._workers[victim].server.journal.chaos = plan
        events: list = []
        cursors = [0] * sessions
        balance_log: list = []
        rounds = {"n": 0}

        def on_round(c):
            rounds["n"] += 1
            if (
                point in CLUSTER_KILL_POINTS
                and rounds["n"] == kill_round
            ):
                c._workers[victim].kill()
            balance_log.append(c.accounting())

        crashed = False
        try:
            _cluster_schedule(
                cluster, recordings, cursors, hop=hop, clock=clock,
                models=models, swap_sample=swap_sample, events=events,
                on_round=on_round,
            )
        except SimulatedCrash:
            crashed = True
        # standby accounting up to the crash instant: a CLUSTER-point
        # kill lands mid-handoff, AFTER the fetch — the counters live
        # on the controller object the takeover replaces
        pre_fpb = cluster.failover_path_bytes
        pre_sf = cluster.standby_fetches
        if not crashed:
            cluster.close()
            return {
                "ok": False, "point": point,
                "why": f"kill point {point!r} never fired (at={at})",
                "windows_lost": 0, "failover_ms": 0.0,
            }

        t0 = time.perf_counter()
        if point in CLUSTER_KILL_POINTS:
            # the controller died; the surviving worker PROCESSES did
            # not — a new controller takes them over and completes the
            # orphaned failover from the journals
            from har_tpu.serve.cluster.controller import FleetCluster

            survivors = [
                w for w in cluster._workers.values() if w.alive
            ]
            cluster = FleetCluster.takeover(
                models["A"], root, survivors,
                config=cluster.config, clock=clock, loader=loader,
            )
        else:
            # the victim worker died at its stage boundary; model the
            # SIGKILL (un-flushed journal suffix gone) and let the
            # still-running controller's failure detector find it
            cluster._workers[victim].kill()
        _cluster_schedule(
            cluster, recordings, cursors, hop=hop, clock=clock,
            models=models, swap_sample=swap_sample, events=events,
            on_round=lambda c: balance_log.append(c.accounting()),
        )
        failover_ms = (time.perf_counter() - t0) * 1e3
        stats = cluster.cluster_stats()
        verdict = _cluster_verdict(
            point, ref_events, events, cluster, balance_log, stats,
            failover_ms,
        )
        if standby:
            # sum across the controller generations: an engine-point
            # kill accrues after the crash on the same object
            # (pre-crash counters are zero), a CLUSTER-point kill
            # accrues before it (the takeover controller starts clean)
            total_sf = pre_sf + cluster.standby_fetches
            total_fpb = pre_fpb + cluster.failover_path_bytes
            verdict.update(
                standby_fetches=total_sf,
                failover_path_bytes=total_fpb,
            )
            if verdict["ok"] and total_sf < 1:
                verdict.update(
                    ok=False,
                    why="failover never sourced from the warm standby",
                )
            elif verdict["ok"] and total_fpb != 0:
                verdict.update(
                    ok=False,
                    why=(
                        f"warm failover moved {total_fpb} byte(s); a "
                        "caught-up standby must transfer zero"
                    ),
                )
        cluster.close()
        return verdict
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _cluster_verdict(point, ref_events, events, cluster, balance_log,
                     stats, failover_ms) -> dict:
    why = None
    keys = [_event_key(e) for e in events]
    if len(keys) != len(set(keys)):
        why = "an event was delivered twice across the worker kill"
    by_sid: dict = {}
    for e in events:
        by_sid.setdefault(e.session_id, []).append(e)
    ref_by_sid: dict = {}
    for e in ref_events:
        ref_by_sid.setdefault(e.session_id, []).append(e)
    windows_lost = sum(len(v) for v in ref_by_sid.values()) - sum(
        len(v) for v in by_sid.values()
    )
    if why is None and windows_lost != 0:
        why = f"{windows_lost} window(s) lost vs the un-killed run"
    if why is None:
        for sid, want in ref_by_sid.items():
            got = by_sid.get(sid, [])
            if [_event_fields(e) for e in got] != [
                _event_fields(e) for e in want
            ]:
                why = (
                    f"session {sid!r} events diverge from the "
                    "un-killed cluster run"
                )
                break
    acct = cluster.accounting()
    if why is None and not (acct["balanced"] and acct["pending"] == 0):
        why = f"global conservation violated at the end: {acct}"
    if why is None:
        for i, snap in enumerate(balance_log):
            if not snap["balanced"] or snap["pending"] < 0:
                why = (
                    f"global conservation violated at snapshot {i}: "
                    f"{snap}"
                )
                break
    if why is None and stats["failovers"] < 1:
        why = "no failover was recorded"
    # the controller's in-memory migration log dies with it in a
    # takeover; the per-worker `migrations` counter is the durable
    # evidence (adopt records replay it), so it is the one checked
    migrated = max(stats["migrated_sessions"], stats["migrations"])
    if why is None and migrated < 1:
        why = "no session was migrated"
    return {
        "ok": why is None,
        "point": point,
        "why": why,
        "workers": stats["workers"],
        "failovers": stats["failovers"],
        "migrated_sessions": migrated,
        "windows_lost": max(windows_lost, 0),
        "migration_ms": stats["migration_ms"],
        "failover_ms": round(failover_ms, 3),
        "delivered": len(events),
        "accounting": acct,
    }
