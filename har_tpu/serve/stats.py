"""Fleet serving observability: counters, gauges, stage histograms.

Every number a fleet operator needs to tell "the engine is slow" from
"the chip is slow" from "the load is too high", in one snapshot:

  - counters: enqueued / scored / dropped (by reason) windows, dispatch
    count/retries/failures, degraded events, admission rejections — the
    accounting invariant ``enqueued == scored + dropped + pending`` is
    checked by ``snapshot()`` itself (``accounting.balanced``);
  - gauges: live queue depth (current + high-water mark), sessions;
  - per-stage latency histograms over the pipeline
    enqueue → batch → dispatch → smooth, plus the end-to-end event
    latency (enqueue→emit) the serving SLO is stated against.

Host-side and allocation-light by design: one histogram record is a
bisect into a fixed bucket table plus a bounded deque append — the
stats path must never become the latency it measures.
"""

from __future__ import annotations

import bisect
from collections import deque

import numpy as np

# log-spaced bucket upper bounds (ms): 0.05 ms .. 50 s, ~half-decade
# steps — wide enough to cover sub-ms CPU-stub smoothing AND multi-
# second degraded-tunnel dispatches in the same table
_BUCKET_BOUNDS_MS = (
    0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
    100.0, 200.0, 500.0, 1000.0, 2000.0, 5000.0, 10000.0, 50000.0,
)


class StageHistogram:
    """Latency histogram for one pipeline stage.

    Fixed log-spaced buckets (cheap, bounded, mergeable into dashboards)
    plus a trailing window of raw samples for exact percentiles — the
    same trailing-window stance as ``StreamingClassifier.latency_stats``
    (a fleet runs for days; stats must stay current and memory
    constant).
    """

    __slots__ = ("count", "total_ms", "max_ms", "buckets", "_recent")

    def __init__(self, window: int = 8192):
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0
        self.buckets = [0] * (len(_BUCKET_BOUNDS_MS) + 1)
        self._recent: deque[float] = deque(maxlen=window)

    def record(self, ms: float) -> None:
        self.count += 1
        self.total_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms
        self.buckets[bisect.bisect_left(_BUCKET_BOUNDS_MS, ms)] += 1
        self._recent.append(ms)

    def record_many(self, ms: np.ndarray) -> None:
        """Record a whole batch of samples in four vectorized
        reductions — the SoA host plane's per-dispatch path (one call
        per dispatch instead of one bisect + append per window).
        ``searchsorted(side="left")`` is ``bisect_left`` exactly, so
        the bucket table is identical to per-sample ``record`` calls;
        ``total_ms`` accumulates via one ``sum`` (the aggregate is a
        float total, not a bit-pinned stream)."""
        n = len(ms)
        if not n:
            return
        # host-origin wall-clock samples; no device buffer anywhere
        # near this path
        ms = np.asarray(ms, np.float64)  # harlint: host-ok
        self.count += n
        self.total_ms += float(ms.sum())  # harlint: host-ok
        top = float(ms.max())  # harlint: host-ok
        if top > self.max_ms:
            self.max_ms = top
        idx = np.searchsorted(_BUCKET_BOUNDS_MS, ms, side="left")
        for b, k in zip(*np.unique(idx, return_counts=True)):
            self.buckets[int(b)] += int(k)
        self._recent.extend(ms.tolist())

    def percentile(self, q: float) -> float | None:
        if not self._recent:
            return None
        return float(
            np.percentile(np.asarray(self._recent, np.float64), q)
        )

    def snapshot(self) -> dict:
        if not self.count:
            return {"count": 0}
        # a freshly restored histogram has durable counts but an empty
        # trailing window (percentiles restart after recovery, by
        # design) — report None, never crash the snapshot
        p50, p99 = self.percentile(50), self.percentile(99)
        out = {
            "count": self.count,
            "mean_ms": round(self.total_ms / self.count, 4),
            "p50_ms": None if p50 is None else round(p50, 4),
            "p99_ms": None if p99 is None else round(p99, 4),
            "max_ms": round(self.max_ms, 4),
        }
        # sparse bucket view: only non-empty buckets, keyed by upper
        # bound — readable in a JSON artifact without 19 zero rows
        bounds = [*map(str, _BUCKET_BOUNDS_MS), "+inf"]
        out["buckets_ms"] = {
            bounds[i]: n for i, n in enumerate(self.buckets) if n
        }
        return out

    # ------------------------------------------- durability (journal)

    def state(self) -> dict:
        """JSON-serializable full state for a recovery snapshot.  The
        trailing raw-sample window is dropped by design: percentiles
        restart after a recovery (they measure THIS process's serving),
        while counts/totals/buckets — the durable aggregates — survive."""
        return {
            "count": self.count,
            "total_ms": self.total_ms,
            "max_ms": self.max_ms,
            "buckets": list(self.buckets),
        }

    def load_state(self, state: dict) -> None:
        """Restore from ``state()`` output; missing keys (pre-journal
        snapshots) keep their zero defaults."""
        self.count = int(state.get("count", 0))
        self.total_ms = float(state.get("total_ms", 0.0))
        self.max_ms = float(state.get("max_ms", 0.0))
        buckets = state.get("buckets")
        if buckets is not None and len(buckets) == len(self.buckets):
            self.buckets = [int(b) for b in buckets]


class HostProfile:
    """Per-poll host-time breakdown for the SoA host plane
    (``har serve --profile-host`` / ``FleetConfig.profile_host``): one
    StageHistogram per scheduler phase —

      ``ingest``     push/push_many wall time (guard + ring writes +
                     window staging) per delivery call,
      ``due_select`` batch selection (queue pop + due bookkeeping) per
                     dispatch,
      ``gather``     staging-arena gather + pad/slab fill per dispatch,
      ``retire``     retire wall (fetch + smoothing + event build +
                     acks) per dispatch,
      ``journal``    end-of-poll ack flush per poll.

    Process-local observability by design (never journaled): the
    breakdown measures THIS process's serving loop — what the
    sessions-per-worker ceiling curve and future host-plane regressions
    read out of the summary JSON.
    """

    PHASES = ("ingest", "due_select", "gather", "retire", "journal")

    def __init__(self):
        for name in self.PHASES:
            setattr(self, name, StageHistogram())
        # pending-queue depth distribution (windows, not ms): the
        # un-launched backlog sampled at poll entry and before every
        # launch (StageHistogram.record_many, one call per poll) — the
        # size axis that makes due-selection cost attributable: a fat
        # due_select histogram with a fat depth histogram is load, with
        # a thin one is a scheduler regression
        self.pending_depth = StageHistogram()

    def snapshot(self) -> dict:
        out = {
            f"{name}_ms": getattr(self, name).snapshot()
            for name in self.PHASES
        }
        out["pending_depth"] = self.pending_depth.snapshot()
        return out


class FleetStats:
    """Counters + gauges + stage histograms for one FleetServer.

    The stage names mirror the pipeline: ``queue_wait`` (enqueue→batch
    assembly), ``dispatch`` (one batched transform, e2e through the
    tunnel), ``smooth`` (per-batch host-side smoothing + event build),
    ``event`` (enqueue→emit, the per-event serving latency the SLO and
    the bench lane's p50/p99 are stated against), ``shadow`` (one
    candidate-model scoring of a mirrored batch — off the serving
    critical path, timed so "the shadow is slow" is observable).

    Adaptation counters (har_tpu.adapt): ``model_swaps`` / ``rollbacks``
    count hot-swap transitions, ``scored_by_version`` attributes every
    scored window to the model version that scored it — summing it
    reproduces ``scored``, so the conservation law ``enqueued == scored
    + dropped + pending`` holds ACROSS a swap, per version and in total.
    ``shadow_batches``/``shadow_windows`` count mirrored scoring
    (never part of ``scored``: shadow work is observability, not
    serving); ``shadow_errors`` counts swallowed shadow failures.
    """

    def __init__(self):
        self.enqueued = 0
        self.scored = 0
        self.dropped: dict[str, int] = {}
        self.dispatches = 0
        self.dispatch_retries = 0
        self.dispatch_failures = 0
        self.degraded_events = 0
        self.smoothing_shed_transitions = 0
        self.slo_breaches = 0
        self.admission_rejections = 0
        # live gauges, recomputed during restore (add_session /
        # note_queue_depth replay) — deliberately not snapshot state
        self.sessions = 0  # harlint: ephemeral
        self.queue_depth = 0  # harlint: ephemeral
        self.queue_depth_max = 0
        self.batch_sizes: dict[int, int] = {}  # padded size -> count
        # ingest guard: non-finite / wildly out-of-range samples refused
        # at push() — never an exception on the serving loop
        self.rejected_samples = 0
        # durability (har_tpu.serve.journal): process restarts this
        # fleet has survived, and windows the pre-crash process enqueued
        # whose data could not be recovered (bounded by the journal
        # flush interval; see FleetServer.declare_lost)
        self.recoveries = 0
        self.lost_in_crash = 0
        # adaptation lifecycle (har_tpu.adapt)
        self.model_swaps = 0
        self.rollbacks = 0
        self.shadow_batches = 0
        self.shadow_windows = 0
        self.shadow_errors = 0
        self.scored_by_version: dict[str, int] = {}
        # edge identity (har_tpu.serve.net.ingest): per-tenant frame
        # accept/shed counts from the gateway's admission ladder — the
        # fairness policy's observable (a storming tenant's sheds grow,
        # a protected tenant's stay zero), persisted like every other
        # dict counter
        self.tenant_accepts: dict[str, int] = {}
        self.tenant_sheds: dict[str, int] = {}
        # pipelined dispatch (har_tpu.serve.dispatch): host-assembly
        # time that ran UNDER an in-flight device batch, total ticket
        # in-flight time (launch end → retire fetch done), the in-flight
        # depth distribution at launch, and windows dispatched per
        # device (sharded meshes split each padded batch evenly)
        self.overlap_host_ms = 0.0
        self.inflight_ms = 0.0
        self.inflight_depth: dict[int, int] = {}
        self.device_windows: dict[str, int] = {}
        # fused hot loop (har_tpu.serve.dispatch, PR 10): dispatches
        # retired through the one fused device program, bytes actually
        # transferred device→host at retire, and bytes the fused
        # (labels, top_probs) fetch saved vs the full logits matrix —
        # the "fetch bytes dropped" evidence the 2× windows/s claim is
        # attributed with
        self.fused_dispatches = 0
        self.fetch_bytes = 0
        self.fetch_bytes_saved = 0
        # cluster control plane (har_tpu.serve.cluster): dead-worker
        # failovers this worker absorbed sessions from, sessions adopted
        # onto this worker via journal hand-off, and the total wall time
        # those hand-offs took (receiver-side; a duration accumulator
        # like overlap_host_ms, not an event count)
        self.worker_failovers = 0
        self.migrations = 0
        self.migration_ms = 0.0
        # elastic capacity (har_tpu.serve.traffic): online resizes this
        # engine has applied (target_batch / pipeline_depth / mesh, at a
        # dispatch boundary — FleetServer.resize), split by capacity
        # direction.  ``utilization`` is the live fill fraction of the
        # most recent dispatched batch (k / target_batch) — the load
        # signal the capacity controller's scale-DOWN evidence reads;
        # recomputed by the next dispatch, deliberately not snapshot
        # state
        self.resizes = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.utilization = 0.0  # harlint: ephemeral
        # memory-footprint gauges (PR 14): resident bytes of the SoA
        # session estate, the staging block and the pending queue —
        # recomputed from the live structures at every stats_snapshot
        # (the 20k-session scaling point is partially memory-bound;
        # these are the "why", stamped into the host_plane gate entry
        # and the scaling-artifact rows), never snapshot state
        self.arena_bytes = 0  # harlint: ephemeral
        self.staging_bytes = 0  # harlint: ephemeral
        self.pending_bytes = 0  # harlint: ephemeral
        # continuous replication (har_tpu.serve.replica): per-source
        # tail lag — records the last standby cycle found staged but
        # not yet applied, and manifest bytes not yet landed locally.
        # Recomputed by every cycle (and from the tailed files after a
        # standby restart), never snapshot state
        self.replication_lag_records: dict = {}  # harlint: ephemeral
        self.replication_lag_bytes: dict = {}  # harlint: ephemeral
        # wire transport (har_tpu.serve.net): RPC round trips issued,
        # deadline-exceeded re-attempts, and bytes moved each way —
        # the comms/serialization term the Spark-perf study says
        # dominates off-box (arXiv 1612.01437), measured not assumed.
        # Worker-side RpcServers and controller-side RpcClients count
        # into their own FleetStats with the same field names.
        self.rpc_sent = 0
        self.rpc_retries = 0
        self.rpc_bytes_tx = 0
        self.rpc_bytes_rx = 0
        # journal shipping (har_tpu.serve.net.ship): bytes and chunks
        # pulled over the wire restoring dead partitions, and transfers
        # that RESUMED from a prior attempt's durable chunk log — the
        # shared-nothing failover's cost/robustness evidence, counted
        # on the controller side like the rpc_* family
        self.shipped_bytes = 0
        self.ship_chunks = 0
        self.ship_resumes = 0
        # storage-fault containment (the journal write-error satellite):
        # flush/fsync failures the engine absorbed as a declared
        # degradation instead of dying — while non-zero since the last
        # clean flush, acks may not be durable and snapshots are refused
        self.journal_write_errors = 0
        # forward-compat guard (the runtime half of harlint HL002):
        # state keys a NEWER writer persisted that this version does
        # not know — counted and warned in load_state, never silently
        # dropped
        self.unknown_state_keys = 0
        self.queue_wait = StageHistogram()
        self.dispatch = StageHistogram()
        self.smooth = StageHistogram()
        self.event = StageHistogram()
        self.shadow = StageHistogram()
        # one RPC round-trip latency histogram (controller side: call
        # issue -> response decoded; the wire_failover bench lane's
        # p50/p99 source)
        self.rpc_rtt = StageHistogram()

    # ------------------------------------------------------- recording

    def drop(self, n: int, reason: str) -> None:
        self.dropped[reason] = self.dropped.get(reason, 0) + n

    @property
    def dropped_total(self) -> int:
        return sum(self.dropped.values())

    def note_queue_depth(self, depth: int) -> None:
        self.queue_depth = depth
        if depth > self.queue_depth_max:
            self.queue_depth_max = depth

    def note_batch(self, padded: int) -> None:
        self.batch_sizes[padded] = self.batch_sizes.get(padded, 0) + 1

    def note_scored(self, n: int, version: str) -> None:
        """n windows scored by model ``version`` — the per-version leg
        of the conservation law (sum over versions == scored)."""
        self.scored += n
        self.scored_by_version[version] = (
            self.scored_by_version.get(version, 0) + n
        )

    def note_tenant_accept(self, tenant: str) -> None:
        """One push frame from ``tenant`` admitted at the edge."""
        self.tenant_accepts[tenant] = (
            self.tenant_accepts.get(tenant, 0) + 1
        )

    def note_tenant_shed(self, tenant: str) -> None:
        """One push frame from ``tenant`` refused (with a receipt) at
        the edge — the fairness ladder's declared refusal."""
        self.tenant_sheds[tenant] = self.tenant_sheds.get(tenant, 0) + 1

    def note_shadow(self, n_windows: int, ms: float) -> None:
        self.shadow_batches += 1
        self.shadow_windows += n_windows
        self.shadow.record(ms)

    def note_inflight_depth(self, depth: int) -> None:
        self.inflight_depth[depth] = self.inflight_depth.get(depth, 0) + 1

    def note_device_windows(self, label: str, n: int) -> None:
        self.device_windows[label] = self.device_windows.get(label, 0) + n

    def overlap_pct(self) -> float | None:
        """Share of device in-flight time covered by concurrent host
        assembly — the number the pipeline exists to raise.  None until
        a pipelined dispatch has flown (depth-1 engines never overlap:
        the launch that would overlap always finds the pipe empty)."""
        if self.inflight_ms <= 0.0 or self.overlap_host_ms <= 0.0:
            return None
        return round(
            min(100.0, 100.0 * self.overlap_host_ms / self.inflight_ms), 1
        )

    # ------------------------------------------------------- reporting

    def accounting(self) -> dict:
        """The conservation law: every enqueued window is exactly one of
        scored, dropped, still pending, or lost in a crash.

        ``lost_in_crash`` counts windows a pre-crash process enqueued
        whose data never reached the durable journal AND whose samples
        the resuming transport declared unreplayable
        (``FleetServer.declare_lost``) — bounded by the journal flush
        interval, zero for transports that re-deliver from the recovered
        watermark."""
        pending = (
            self.enqueued
            - self.scored
            - self.dropped_total
            - self.lost_in_crash
        )
        return {
            "enqueued": self.enqueued,
            "scored": self.scored,
            "dropped": self.dropped_total,
            "pending": pending,
            "lost_in_crash": self.lost_in_crash,
            # balanced now ALSO requires the per-version attribution to
            # conserve: a swap that lost or double-counted a window
            # would break scored_by_version before it broke the total
            "balanced": (
                pending >= 0
                and sum(self.scored_by_version.values()) == self.scored
            ),
        }

    def snapshot(self) -> dict:
        """One JSON-ready dict: the FleetStats export surface (stamped
        into bench artifacts and the release gate log)."""
        return {
            "sessions": self.sessions,
            "queue_depth": self.queue_depth,
            "queue_depth_max": self.queue_depth_max,
            "dispatches": self.dispatches,
            "dispatch_retries": self.dispatch_retries,
            "dispatch_failures": self.dispatch_failures,
            "degraded_events": self.degraded_events,
            "smoothing_shed_transitions": self.smoothing_shed_transitions,
            "slo_breaches": self.slo_breaches,
            "admission_rejections": self.admission_rejections,
            "dropped_by_reason": dict(self.dropped),
            "rejected_samples": self.rejected_samples,
            "recoveries": self.recoveries,
            "batch_sizes": {
                str(k): v for k, v in sorted(self.batch_sizes.items())
            },
            "model_swaps": self.model_swaps,
            "rollbacks": self.rollbacks,
            "shadow_batches": self.shadow_batches,
            "shadow_windows": self.shadow_windows,
            "shadow_errors": self.shadow_errors,
            "worker_failovers": self.worker_failovers,
            "migrations": self.migrations,
            "migration_ms": round(self.migration_ms, 3),
            "rpc_sent": self.rpc_sent,
            "rpc_retries": self.rpc_retries,
            "rpc_bytes_tx": self.rpc_bytes_tx,
            "rpc_bytes_rx": self.rpc_bytes_rx,
            "shipped_bytes": self.shipped_bytes,
            "ship_chunks": self.ship_chunks,
            "ship_resumes": self.ship_resumes,
            "journal_write_errors": self.journal_write_errors,
            "resizes": self.resizes,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "utilization": round(self.utilization, 4),
            "arena_bytes": self.arena_bytes,
            "staging_bytes": self.staging_bytes,
            "pending_bytes": self.pending_bytes,
            "replication_lag_records": dict(
                self.replication_lag_records
            ),
            "replication_lag_bytes": dict(self.replication_lag_bytes),
            "unknown_state_keys": self.unknown_state_keys,
            "scored_by_version": dict(self.scored_by_version),
            "tenant_accepts": dict(self.tenant_accepts),
            "tenant_sheds": dict(self.tenant_sheds),
            "fused_dispatches": self.fused_dispatches,
            "fetch_bytes": self.fetch_bytes,
            "fetch_bytes_saved": self.fetch_bytes_saved,
            "overlap_pct": self.overlap_pct(),
            "overlap_host_ms": round(self.overlap_host_ms, 3),
            "inflight_ms": round(self.inflight_ms, 3),
            "inflight_depth": {
                str(k): v for k, v in sorted(self.inflight_depth.items())
            },
            "device_windows": dict(self.device_windows),
            "accounting": self.accounting(),
            "stages": {
                "queue_wait_ms": self.queue_wait.snapshot(),
                "dispatch_ms": self.dispatch.snapshot(),
                "smooth_ms": self.smooth.snapshot(),
                "event_ms": self.event.snapshot(),
                "shadow_ms": self.shadow.snapshot(),
                "rpc_rtt_ms": self.rpc_rtt.snapshot(),
            },
        }

    # ------------------------------------------- durability (journal)

    _COUNTERS = (
        "enqueued", "scored", "dispatches", "dispatch_retries",
        "dispatch_failures", "degraded_events",
        "smoothing_shed_transitions", "slo_breaches",
        "admission_rejections", "queue_depth_max", "rejected_samples",
        "recoveries", "lost_in_crash", "model_swaps", "rollbacks",
        "shadow_batches", "shadow_windows", "shadow_errors",
        "worker_failovers", "migrations",
        "resizes", "scale_ups", "scale_downs",
        "fused_dispatches", "fetch_bytes", "fetch_bytes_saved",
        "rpc_sent", "rpc_retries", "rpc_bytes_tx", "rpc_bytes_rx",
        "shipped_bytes", "ship_chunks", "ship_resumes",
        "journal_write_errors",
        "unknown_state_keys",
    )
    _STAGES = (
        "queue_wait", "dispatch", "smooth", "event", "shadow", "rpc_rtt"
    )
    # the state() envelope: every top-level key a state dict may carry.
    # load_state counts anything outside this set (or outside
    # _COUNTERS/_STAGES within it) as an unknown key and warns.
    _STATE_KEYS = (
        "counters", "dropped", "batch_sizes", "scored_by_version",
        "tenant_accepts", "tenant_sheds",
        "overlap_host_ms", "inflight_ms", "inflight_depth",
        "device_windows", "migration_ms", "stages",
    )

    def state(self) -> dict:
        """JSON-serializable full counter state for a recovery snapshot
        (har_tpu.serve.journal).  Every field the conservation law and
        the per-version attribution need survives a crash; histogram
        trailing windows restart (see StageHistogram.state)."""
        return {
            "counters": {k: getattr(self, k) for k in self._COUNTERS},
            "dropped": dict(self.dropped),
            "batch_sizes": {str(k): v for k, v in self.batch_sizes.items()},
            "scored_by_version": dict(self.scored_by_version),
            "tenant_accepts": dict(self.tenant_accepts),
            "tenant_sheds": dict(self.tenant_sheds),
            "overlap_host_ms": self.overlap_host_ms,
            "inflight_ms": self.inflight_ms,
            "migration_ms": self.migration_ms,
            "inflight_depth": {
                str(k): v for k, v in self.inflight_depth.items()
            },
            "device_windows": dict(self.device_windows),
            "stages": {
                name: getattr(self, name).state() for name in self._STAGES
            },
        }

    def load_state(self, state: dict) -> None:
        """Restore from ``state()`` output.  Pre-journal state dicts
        missing the newer fields (``lost_in_crash``, ``recoveries``,
        ``rejected_samples``, and the pre-pipeline overlap/in-flight
        fields) load with zero defaults — back-compat is pinned in the
        test suite.  Keys this version does NOT know (a newer writer's
        state) are never silently dropped: they are counted in
        ``unknown_state_keys`` and warned about, so a forward-compat
        downgrade degrades loudly (pinned in tests/test_recovery.py)."""
        unknown = [
            k for k in (state.get("counters") or {})
            if k not in self._COUNTERS
        ]
        unknown += [k for k in state if k not in self._STATE_KEYS]
        unknown += [
            k for k in (state.get("stages") or {}) if k not in self._STAGES
        ]
        for k, v in (state.get("counters") or {}).items():
            if k in self._COUNTERS:
                setattr(self, k, int(v))
        if unknown:
            import warnings

            self.unknown_state_keys += len(unknown)
            warnings.warn(
                "FleetStats.load_state: ignoring unknown state keys "
                f"{sorted(unknown)} — written by a newer version? "
                "(counted in unknown_state_keys)",
                RuntimeWarning,
                stacklevel=2,
            )
        self.overlap_host_ms = float(state.get("overlap_host_ms", 0.0))
        self.inflight_ms = float(state.get("inflight_ms", 0.0))
        # pre-cluster state dicts lack migration_ms: default 0.0
        self.migration_ms = float(state.get("migration_ms", 0.0))
        self.inflight_depth = {
            int(k): int(v)
            for k, v in (state.get("inflight_depth") or {}).items()
        }
        self.device_windows = {
            str(k): int(v)
            for k, v in (state.get("device_windows") or {}).items()
        }
        self.dropped = {
            str(k): int(v) for k, v in (state.get("dropped") or {}).items()
        }
        self.batch_sizes = {
            int(k): int(v)
            for k, v in (state.get("batch_sizes") or {}).items()
        }
        self.scored_by_version = {
            str(k): int(v)
            for k, v in (state.get("scored_by_version") or {}).items()
        }
        # pre-tenant state dicts lack the edge identity counters: the
        # zero default IS the back-compat contract (test-pinned)
        self.tenant_accepts = {
            str(k): int(v)
            for k, v in (state.get("tenant_accepts") or {}).items()
        }
        self.tenant_sheds = {
            str(k): int(v)
            for k, v in (state.get("tenant_sheds") or {}).items()
        }
        for name, st in (state.get("stages") or {}).items():
            if name in self._STAGES:
                getattr(self, name).load_state(st)
