"""Fleet serving: continuous batching for thousands of concurrent
20 Hz accelerometer streams over one compiled predict path.

Public surface:
  FleetServer / FleetConfig / FleetEvent  — the engine (engine.py)
  FleetStats / HostProfile                — observability (stats.py)
  SessionArena / PendingArena             — structure-of-arrays session
                                            + pending-queue estates
                                            (arena.py): rings, heads/
                                            fills, smoother state,
                                            counters and the queued-
                                            window FIFO as contiguous
                                            slot-indexed arrays
  DispatchTicket / StagingArena / make_scorer — pipelined dispatch
                                            plane (dispatch.py)
  DispatchFaults / DeliveryFaults / FakeClock — fault injection
  AnalyticDemoModel / JitDemoModel / synthetic_sessions / drive_fleet
                                          — load generation
  FleetJournal / JournalConfig            — durability (journal.py)
  restore_server / recovery_smoke         — crash recovery (recover.py)
  KILL_POINTS / run_kill_point            — kill-point chaos (chaos.py)
  CLUSTER_KILL_POINTS / run_cluster_kill_point — worker-axis chaos
  SHIP_KILL_POINTS                        — journal-ship transfer chaos
  NET_PARTITION_CASES                     — partition-tolerance matrix
                                            (runners in serve/net/chaos)
  fleet_slo_smoke / fleet_pipeline_smoke  — the release gate's checks
  har_tpu.serve.cluster                   — multi-worker control plane
                                            (FleetCluster: router,
                                            heartbeat failover, journal
                                            hand-off migration)
  har_tpu.serve.net                       — REAL multi-host transport
                                            (NetCluster over `har
                                            serve-worker` subprocesses:
                                            CRC-framed TCP RPCs with
                                            deadlines/retries, NetWorker
                                            proxies, replicated
                                            controller election, the
                                            wire chaos + partition
                                            matrices)
  har_tpu.serve.traffic                   — elastic traffic engine
                                            (TrafficTrace: diurnal/
                                            bursty/storm churn loadgen;
                                            CapacityController: online
                                            target_batch / depth / mesh
                                            / worker-count autoscaling;
                                            elastic_smoke)

See docs/serving.md for the architecture and the equivalence contract,
docs/recovery.md for the journal format and the recovery invariants.
"""

from har_tpu.serve.chaos import (
    CLUSTER_KILL_POINTS,
    NET_PARTITION_CASES,
    ENGINE_KILL_POINTS,
    KILL_POINTS,
    SHIP_KILL_POINTS,
    TAIL_KILL_POINTS,
    KillPlan,
    SimulatedCrash,
    run_cluster_kill_point,
    run_kill_point,
    run_random_kill,
    run_tail_kill_point,
)
from har_tpu.serve.arena import PendingArena, SessionArena
from har_tpu.serve.dispatch import (
    DispatchTicket,
    StagingArena,
    make_scorer,
)
from har_tpu.serve.engine import (
    AdmissionError,
    DispatchError,
    FleetConfig,
    FleetEvent,
    FleetServer,
)
from har_tpu.serve.faults import (
    DeliveryFaults,
    DispatchFaults,
    FakeClock,
    InjectedDispatchFailure,
)
from har_tpu.serve.journal import (
    FleetJournal,
    JournalConfig,
    JournalError,
)
from har_tpu.serve.loadgen import (
    AnalyticDemoModel,
    HostPlaneStubModel,
    JitDemoModel,
    LoadReport,
    drive_fleet,
    host_plane_benchmark,
    host_plane_summary,
    synthetic_sessions,
)
from har_tpu.serve.recover import (
    RecoveryError,
    recovery_smoke,
    restore_server,
)
from har_tpu.serve.replica import StandbyAgent, StandbyHost, WarmReplica
from har_tpu.serve.slo import (
    events_equal,
    fleet_pipeline_smoke,
    fleet_slo_smoke,
)
from har_tpu.serve.stats import FleetStats, HostProfile, StageHistogram
from har_tpu.serve.traffic import (
    AutoscaleConfig,
    CapacityController,
    TraceReport,
    TraceSpec,
    TrafficTrace,
    drive_trace,
    elastic_smoke,
)

__all__ = [
    "AdmissionError",
    "AnalyticDemoModel",
    "AutoscaleConfig",
    "CapacityController",
    "TraceReport",
    "TraceSpec",
    "TrafficTrace",
    "elastic_smoke",
    "CLUSTER_KILL_POINTS",
    "NET_PARTITION_CASES",
    "run_cluster_kill_point",
    "DeliveryFaults",
    "DispatchError",
    "DispatchFaults",
    "DispatchTicket",
    "ENGINE_KILL_POINTS",
    "FakeClock",
    "FleetConfig",
    "FleetEvent",
    "FleetJournal",
    "FleetServer",
    "FleetStats",
    "HostPlaneStubModel",
    "HostProfile",
    "InjectedDispatchFailure",
    "JitDemoModel",
    "JournalConfig",
    "JournalError",
    "KILL_POINTS",
    "SHIP_KILL_POINTS",
    "TAIL_KILL_POINTS",
    "KillPlan",
    "LoadReport",
    "PendingArena",
    "RecoveryError",
    "SessionArena",
    "SimulatedCrash",
    "StandbyAgent",
    "StandbyHost",
    "StageHistogram",
    "StagingArena",
    "drive_fleet",
    "host_plane_benchmark",
    "host_plane_summary",
    "drive_trace",
    "events_equal",
    "fleet_pipeline_smoke",
    "fleet_slo_smoke",
    "make_scorer",
    "recovery_smoke",
    "restore_server",
    "run_kill_point",
    "run_random_kill",
    "run_tail_kill_point",
    "synthetic_sessions",
    "WarmReplica",
]
