"""Fleet serving: continuous batching for thousands of concurrent
20 Hz accelerometer streams over one compiled predict path.

Public surface:
  FleetServer / FleetConfig / FleetEvent  — the engine (engine.py)
  FleetStats                              — observability (stats.py)
  DispatchFaults / DeliveryFaults / FakeClock — fault injection
  AnalyticDemoModel / synthetic_sessions / drive_fleet — load generation
  fleet_slo_smoke                         — the release gate's check

See docs/serving.md for the architecture and the equivalence contract.
"""

from har_tpu.serve.engine import (
    AdmissionError,
    DispatchError,
    FleetConfig,
    FleetEvent,
    FleetServer,
)
from har_tpu.serve.faults import (
    DeliveryFaults,
    DispatchFaults,
    FakeClock,
    InjectedDispatchFailure,
)
from har_tpu.serve.loadgen import (
    AnalyticDemoModel,
    LoadReport,
    drive_fleet,
    synthetic_sessions,
)
from har_tpu.serve.slo import events_equal, fleet_slo_smoke
from har_tpu.serve.stats import FleetStats, StageHistogram

__all__ = [
    "AdmissionError",
    "AnalyticDemoModel",
    "DeliveryFaults",
    "DispatchError",
    "DispatchFaults",
    "FakeClock",
    "FleetConfig",
    "FleetEvent",
    "FleetServer",
    "FleetStats",
    "InjectedDispatchFailure",
    "LoadReport",
    "StageHistogram",
    "drive_fleet",
    "events_equal",
    "fleet_slo_smoke",
    "synthetic_sessions",
]
