"""Fault injection for the fleet serving engine — prove the degradation
paths, don't hope at them.

Two fault surfaces, matching the two places a deployed fleet actually
breaks:

  ``DispatchFaults`` — a ``FleetServer(fault_hook=...)`` callable that
    simulates the chip/tunnel side: periodic dispatch stalls (SLO
    breach → smoothing shed → scoring shed ladder) and transient
    dispatch failures (retry path, then drop-batch path).  Stalls can
    either really sleep or advance an injected fake clock, so scheduler
    tests run deterministically in microseconds.

  ``DeliveryFaults`` — transport-side sample-delivery faults applied by
    the load generator (har_tpu.serve.loadgen): dropped chunks (samples
    lost in transport), delayed chunks (held and delivered with the
    next round — which is exactly a catch-up burst), and forced bursts.
    Per-session in-order delivery is preserved — reordering within one
    sensor's TCP-like stream is not a fault mode worth simulating.

Everything is seeded: the same spec produces the same fault schedule.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np


class InjectedDispatchFailure(RuntimeError):
    """Raised by DispatchFaults to simulate a failed dispatch."""


@dataclasses.dataclass
class DispatchFaults:
    """Callable fault hook for FleetServer's dispatch path.

    stall_every / stall_ms:
        every Nth dispatch attempt stalls by stall_ms (0 = never).
    fail_every:
        every Nth dispatch attempt raises InjectedDispatchFailure
        (0 = never); with FleetConfig.retries >= 1 a lone failure is
        absorbed by the retry path.
    fake_clock:
        a ``FakeClock`` (or anything with ``advance(seconds)``): stalls
        advance it instead of sleeping, keeping tests instant.
    """

    stall_every: int = 0
    stall_ms: float = 0.0
    fail_every: int = 0
    fake_clock: object = None
    attempts: int = 0

    def __call__(self, windows: np.ndarray) -> None:
        self.attempts += 1
        if self.stall_every and self.attempts % self.stall_every == 0:
            if self.fake_clock is not None:
                self.fake_clock.advance(self.stall_ms / 1e3)
            else:
                time.sleep(self.stall_ms / 1e3)
        if self.fail_every and self.attempts % self.fail_every == 0:
            raise InjectedDispatchFailure(
                f"injected failure at dispatch attempt {self.attempts}"
            )


class FakeClock:
    """Deterministic monotonic clock for scheduler tests: pass
    ``clock=fake`` to FleetServer and ``fake_clock=fake`` to
    DispatchFaults; advance it explicitly to cross deadlines."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, seconds: float) -> float:
        self.t += float(seconds)
        return self.t


class JournalFaults:
    """Deterministic storage faults for the FleetJournal fault hook
    (``FleetJournal.fault``): raise an OSError at the Nth occurrence of
    the chosen journal operation — ``"write"`` (the segment append),
    ``"fsync"`` (durability sync), or ``"snapshot"`` (the atomic
    snapshot write).  ``times`` consecutive occurrences fail from that
    point (a disk that stays full), then the hook goes quiet (space
    freed) — counter-based, no RNG, so a containment test replays
    exactly.  ``errno_code`` defaults to ENOSPC; pass ``errno.EIO`` for
    the dying-disk flavor."""

    def __init__(self, op: str, at: int = 1, times: int = 1,
                 errno_code: int | None = None):
        import errno

        if op not in ("write", "fsync", "snapshot"):
            raise ValueError(f"unknown journal fault op {op!r}")
        self.op = op
        self.at = int(at)
        self.times = int(times)
        self.errno_code = (
            errno.ENOSPC if errno_code is None else int(errno_code)
        )
        self.hits = 0
        self.fired = 0

    def __call__(self, op: str) -> None:
        if op != self.op:
            return
        self.hits += 1
        if self.at <= self.hits < self.at + self.times:
            self.fired += 1
            raise OSError(
                self.errno_code,
                f"injected journal {op} fault "
                f"(occurrence {self.hits})",
            )


@dataclasses.dataclass(frozen=True)
class DeliveryFaults:
    """Transport-side fault probabilities for the load generator.

    drop_prob:   a delivery chunk is lost (its samples never arrive —
                 downstream windows shift, exactly like a real sensor
                 outage).
    delay_prob:  a chunk is held one delivery round and prepended to the
                 session's next delivery (a catch-up burst).
    burst_prob:  a session delivers its next several rounds at once
                 (burst_rounds chunks in one push).
    burst_rounds: chunks per forced burst.
    """

    drop_prob: float = 0.0
    delay_prob: float = 0.0
    burst_prob: float = 0.0
    burst_rounds: int = 4

    def any(self) -> bool:
        return bool(self.drop_prob or self.delay_prob or self.burst_prob)
