"""Continuous journal tailing: the PR-14 ship protocol pointed at a
moving target.

``fetch_journal`` (serve/net/ship.py) ships a DEAD worker's journal at
failover time — correct, but the failover latency then carries the
whole transfer (``ship_ms`` dominates the ``journal_ship`` bench
lane).  This module reuses the same durable machinery — the fsynced
``ship.log``, idempotent-by-offset chunk pulls, whole-file sha256
verdicts — to follow a LIVE worker's journal continuously, so that by
the time a failover needs the bytes they are already local and the
failover path transfers ~0.

What changes when the source is alive, and how each case is handled:

  the active segment grows    the highest-index ``wal.<k>.log`` is
              append-only; each pass pulls the suffix ``[durable_off,
              manifest_size)`` into ``<name>.part`` and records every
              chunk in ``ship.log``.  The ``.part`` is NEVER renamed
              while tailing — its manifest digest is stale the moment
              it is taken — so a half-tailed destination can never be
              restored by accident (``load_journal``'s
              digest-before-replay guard sees ``ship.log`` without
              ``ship.done`` and refuses);

  sealed files are immutable  snapshot files and all-but-the-highest
              segment never change once listed: they pull exactly like
              a dead ship — digest-verified, renamed into place,
              ``ship_file``-logged;

  the file set changes shape  ``write_snapshot`` rotates to a fresh
              segment and prunes the old ones, always together — so a
              manifest whose FILE NAMES changed marks the one
              re-manifest boundary.  The tail appends a
              ``ship_remanifest`` record (replayed by
              ``ship.replay_ship_log`` — harlint HL003 pins the
              writer↔handler bijection), prunes local files the new
              manifest dropped, and keeps durable offsets for files
              that survived (the active segment a snapshot sealed is
              gone from the manifest; its records live on inside the
              new snapshot);

  the source races a pass     a chunk request can lose a race with the
              source's prune (the file vanished under the manifest in
              hand).  That is a STALENESS signal, not corruption: the
              pass ends early and the next cycle re-manifests.

``finalize_tail`` is the failover half: the source is dead and static,
so the remaining suffix (zero bytes when the tail was caught up) pulls
through the same chunk loop, every file's whole-file sha256 verifies
against the final manifest, and only then do ``ship_done`` + the done
marker land — from that instant the destination restores through the
unchanged ``FleetServer.restore`` path, guard on.  A destination that
holds a PRE-replication ``ship.log`` (the PR-14 failover path died
mid-fetch) finalizes identically: the record vocabulary is shared, so
the tailing client IS the resume path for old logs.

Chaos points (declared in ``serve/chaos.py``, TAIL_KILL_POINTS):
``mid_tail_recv`` between chunk pulls (the standby dies mid-tail and
must resume from ``ship.log`` without re-pulling a durable byte),
``mid_tail_remanifest`` at the re-manifest boundary, and
``post_tail_verify`` after finalize's digests verify but before
``ship_done`` (the retry must be idempotent).
"""

from __future__ import annotations

import os
import shutil
from typing import Callable

from har_tpu.serve.journal import (
    SHIP_DONE,
    SHIP_LOG,
    _SEG_PREFIX,
    _SEG_SUFFIX,
    _SNAP_PREFIX,
)
from har_tpu.serve.net.ship import (
    DEFAULT_CHUNK_BYTES,
    ShipError,
    ShipUnavailable,
    _check_rel,
    _sha256,
    _ShipJournal,
    _write_done_marker,
    journal_manifest,
    replay_ship_log,
)
from har_tpu.utils.durable import fsync_dir


class LocalShipSource:
    """The ``ShipClient`` read surface over a locally visible root of
    journal directories — no RPC, no agent process.  The in-process
    chaos cells (``serve/chaos.py``) and the unit tests tail through
    this, so the tail/finalize logic is exercised identically whether
    the bytes cross a socket or not; it also models the shared-disk
    deployment where a standby can read the workers' journals
    directly but still wants the durable-resume + digest discipline."""

    def __init__(self, root: str):
        self.root = os.path.abspath(os.path.expanduser(root))

    def _dir(self, name) -> str:
        path = os.path.join(self.root, _check_rel(str(name)))
        if not os.path.isdir(path):
            raise ShipError(f"no journal directory {name!r} under "
                            f"{self.root}")
        return path

    def list(self) -> list[dict]:
        dirs = []
        for name in sorted(os.listdir(self.root)):
            path = os.path.join(self.root, name)
            if not os.path.isdir(path):
                continue
            holds_journal = any(
                n.startswith((_SEG_PREFIX, _SNAP_PREFIX))
                for n in os.listdir(path)
            )
            if holds_journal:
                dirs.append({"name": name, "retired": False})
        return dirs

    def retired(self, src: str) -> bool:
        return False

    def manifest(self, src: str) -> list[dict]:
        return journal_manifest(self._dir(src))

    def chunk(self, src: str, f: str, off: int, n: int):
        path = os.path.join(self._dir(src), _check_rel(str(f)))
        try:
            with open(path, "rb") as fh:
                fh.seek(int(off))
                data = fh.read(int(n))
            size = os.path.getsize(path)
        except OSError as exc:
            # the file vanished under the manifest (the source pruned
            # at a rotation): same taxonomy as the agent's refusal
            raise ShipError(f"local ship source: {exc}") from exc
        return (
            {"f": f, "off": int(off), "n": len(data),
             "eof": int(off) + len(data) >= size},
            data,
        )

    def close(self) -> None:
        pass


# ------------------------------------------------------ manifest shape


def _segment_index(rel: str) -> int | None:
    """``wal.<k>.log`` -> k; None for snapshot files."""
    if not (rel.startswith(_SEG_PREFIX) and rel.endswith(_SEG_SUFFIX)):
        return None
    try:
        return int(rel[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
    except ValueError:
        return None


def manifest_base(names) -> int:
    """The snapshot rotation index a manifest is anchored at — the
    re-manifest boundary's identity."""
    for rel in names:
        head = rel.split("/", 1)[0]
        if head.startswith(_SNAP_PREFIX):
            try:
                return int(head[len(_SNAP_PREFIX):])
            except ValueError:
                continue
    return -1


def _active_segment(names) -> str | None:
    """The highest-index segment — the one file a live source still
    appends to; everything else in the manifest is immutable."""
    best, best_idx = None, -1
    for rel in names:
        idx = _segment_index(rel)
        if idx is not None and idx > best_idx:
            best, best_idx = rel, idx
    return best


def staged_bytes(dest: str, names) -> int:
    """Locally landed bytes of the manifest's files (finals plus
    ``.part`` tails) — the numerator of the lag_bytes gauge."""
    total = 0
    for rel in names:
        final = os.path.join(dest, rel)
        if os.path.exists(final):
            total += os.path.getsize(final)
        elif os.path.exists(final + ".part"):
            total += os.path.getsize(final + ".part")
    return total


def _prune_tail(dest: str, names) -> None:
    """Drop local files the new manifest no longer lists (the sealed
    segment a snapshot superseded, the previous snapshot's dir) —
    everything except the ship log itself and the done marker."""
    keep = set(names) | {SHIP_LOG, SHIP_DONE}
    keep_heads = {rel.split("/", 1)[0] for rel in keep}
    for name in sorted(os.listdir(dest)):
        path = os.path.join(dest, name)
        if os.path.isdir(path):
            if name not in keep_heads:
                shutil.rmtree(path, ignore_errors=True)
            continue
        rel = name[:-5] if name.endswith(".part") else name
        if rel not in keep:
            try:
                os.remove(path)
            except OSError:
                pass


# ----------------------------------------------------------- the pulls


def _pull_file(source, src, name, target, dest, ship_journal, off,
               chunk_bytes, _chaos, stats, out) -> int:
    """Chunk-pull ``name`` up to byte ``target`` into ``name + .part``,
    recording each landed chunk durably — the shared loop under tailing
    and finalize.  Bytes past the durable offset (a crash between the
    write and its record) are truncated first, exactly like
    ``_fetch_file``; returns the new durable offset."""
    part = os.path.join(dest, name) + ".part"
    with open(part, "ab") as fh:
        if fh.tell() > off:
            fh.truncate(off)
        while off < target:
            _chaos("mid_tail_recv")
            meta, payload = source.chunk(
                src, name, off, min(chunk_bytes, target - off)
            )
            if (
                meta.get("f") != name
                or int(meta.get("off", -1)) != off
                or int(meta.get("n", -1)) != len(payload)
            ):
                raise ShipUnavailable(
                    f"mis-sequenced tail chunk for {name!r}: asked "
                    f"off={off}, got {meta}"
                )
            if not payload:
                # shorter than the manifest in hand: the source moved
                # on (pruned/rotated) — staleness, not corruption
                raise ShipError(
                    f"short read tailing {name!r} at off={off} — the "
                    "manifest went stale under the pass"
                )
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
            ship_journal.append(
                {"t": "ship_chunk", "f": name, "off": off,
                 "n": len(payload)}
            )
            off += len(payload)
            out["bytes"] += len(payload)
            out["chunks"] += 1
            if stats is not None:
                stats.shipped_bytes += len(payload)
                stats.ship_chunks += 1
    return off


def _land_immutable(source, src, entry, dest, ship_journal, prog,
                    chunk_bytes, _chaos, stats, out) -> None:
    """Pull + verify + rename one immutable manifest entry (snapshot
    file or sealed segment).  A refused digest voids the durable
    progress (``ship_void``) so the next pass re-pulls from zero —
    tailing retries across passes instead of spinning inside one."""
    name = entry["f"]
    final = os.path.join(dest, name)
    parent = os.path.dirname(final)
    if parent != dest:
        os.makedirs(parent, exist_ok=True)
    if (
        os.path.exists(final)
        and os.path.getsize(final) == int(entry["size"])
        and _sha256(final) == entry["sha256"]
    ):
        # crashed between the rename and its log record
        ship_journal.append({"t": "ship_file", "f": name})
        return
    off = _pull_file(
        source, src, name, int(entry["size"]), dest, ship_journal,
        prog.offsets.get(name, 0), chunk_bytes, _chaos, stats, out,
    )
    part = final + ".part"
    if _sha256(part) == entry["sha256"]:
        os.replace(part, final)
        fsync_dir(os.path.dirname(final))
        ship_journal.append({"t": "ship_file", "f": name})
        out["files"] += 1
        return
    try:
        os.remove(part)
    except OSError:
        pass
    ship_journal.append({"t": "ship_void", "f": name})
    raise ShipError(
        f"tailed copy of {name!r} failed its whole-file digest — "
        "voided; the next pass re-pulls it from offset 0"
    )


# ------------------------------------------------------------- tailing


def tail_once(
    source,
    src: str,
    dest: str,
    *,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    chaos: Callable[[str], None] | None = None,
    stats=None,
) -> dict:
    """One tailing pass: re-manifest if the source's file set changed
    shape, land any immutable files, pull the active segment's suffix.
    Returns ``{bytes, chunks, files, remanifests, stale, base,
    manifest_bytes, staged_bytes}`` — ``stale`` means the pass lost a
    race with the source (it rotated mid-pass) and the next cycle will
    re-manifest; every byte that DID land is durable regardless.
    Raises ``ShipUnavailable`` only when the source is unreachable
    outright (the standby parks that source and retries next cycle)."""
    os.makedirs(dest, exist_ok=True)

    def _chaos(point: str) -> None:
        if chaos is not None:
            chaos(point)

    out = {"bytes": 0, "chunks": 0, "files": 0, "remanifests": 0,
           "stale": False, "base": -1, "manifest_bytes": 0,
           "staged_bytes": 0}
    manifest = source.manifest(src)
    names = [e["f"] for e in manifest]
    out["base"] = manifest_base(names)
    out["manifest_bytes"] = sum(int(e["size"]) for e in manifest)
    prog = replay_ship_log(dest)
    ship_journal = _ShipJournal(dest)
    try:
        if prog.manifest is None:
            ship_journal.append(
                {"t": "ship_begin", "src": src, "files": manifest}
            )
        elif [e["f"] for e in prog.manifest] != names:
            # the one point where a live source changes shape: a
            # snapshot rotated the segment set (write_snapshot pairs
            # them by construction)
            _chaos("mid_tail_remanifest")
            ship_journal.append(
                {"t": "ship_remanifest", "src": src, "files": manifest}
            )
            _prune_tail(dest, names)
            keep = set(names)
            prog.offsets = {
                f: o for f, o in prog.offsets.items() if f in keep
            }
            prog.done_files = {
                f for f in prog.done_files if f in keep
            }
            out["remanifests"] = 1
        active = _active_segment(names)
        try:
            for entry in manifest:
                name = _check_rel(entry["f"])
                if name in prog.done_files:
                    continue
                if name == active:
                    # append-only: pull the suffix, never finalize —
                    # the manifest digest of a growing file is stale
                    # by the time it arrives
                    _pull_file(
                        source, src, name, int(entry["size"]), dest,
                        ship_journal, prog.offsets.get(name, 0),
                        chunk_bytes, _chaos, stats, out,
                    )
                else:
                    _land_immutable(
                        source, src, entry, dest, ship_journal, prog,
                        chunk_bytes, _chaos, stats, out,
                    )
        except ShipError as exc:
            if isinstance(exc, ShipUnavailable):
                raise
            out["stale"] = True
    finally:
        ship_journal.close()
    out["staged_bytes"] = staged_bytes(dest, names)
    return out


def finalize_tail(
    source,
    src: str,
    dest: str,
    *,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    chaos: Callable[[str], None] | None = None,
    stats=None,
    reships: int = 2,
) -> dict:
    """Failover completion over a (possibly partial, possibly empty)
    tail: the source is dead and its manifest final, so pull whatever
    suffix is still missing, verify EVERY file's whole-file sha256
    against the final manifest, land ``ship_done`` + the done marker.
    ``out["bytes"]`` is the failover-path transfer — ZERO for a
    caught-up tail, the missing suffix otherwise, the whole journal
    when no standby ever tailed (which makes this a superset of
    ``fetch_journal``'s resume semantics: a PR-14 ship log finalizes
    here unchanged).  Idempotent under crash-and-retry at every
    boundary; ``ShipError`` after the re-ship budget means the source
    is provably corrupt and is a refusal to restore."""
    os.makedirs(dest, exist_ok=True)

    def _chaos(point: str) -> None:
        if chaos is not None:
            chaos(point)

    out = {"bytes": 0, "chunks": 0, "files": 0, "resumes": 0}
    prog = replay_ship_log(dest)
    if prog.done:
        _write_done_marker(dest)
        return out
    manifest = source.manifest(src)
    names = [e["f"] for e in manifest]
    ship_journal = _ShipJournal(dest)
    try:
        if prog.manifest is None:
            ship_journal.append(
                {"t": "ship_begin", "src": src, "files": manifest}
            )
        else:
            out["resumes"] = 1
            if [e["f"] for e in prog.manifest] != names:
                # the worker snapshotted after the last cycle and died
                # before another ran: adopt the final shape
                ship_journal.append(
                    {"t": "ship_remanifest", "src": src,
                     "files": manifest}
                )
                _prune_tail(dest, names)
                keep = set(names)
                prog.offsets = {
                    f: o for f, o in prog.offsets.items() if f in keep
                }
                prog.done_files = {
                    f for f in prog.done_files if f in keep
                }
        for entry in manifest:
            name = _check_rel(entry["f"])
            if name in prog.done_files:
                continue
            final = os.path.join(dest, name)
            parent = os.path.dirname(final)
            if parent != dest:
                os.makedirs(parent, exist_ok=True)
            if (
                os.path.exists(final)
                and os.path.getsize(final) == int(entry["size"])
                and _sha256(final) == entry["sha256"]
            ):
                ship_journal.append({"t": "ship_file", "f": name})
                continue
            off = prog.offsets.get(name, 0)
            attempts = 0
            while True:
                off = _pull_file(
                    source, src, name, int(entry["size"]), dest,
                    ship_journal, off, chunk_bytes, _chaos, stats, out,
                )
                part = final + ".part"
                if _sha256(part) == entry["sha256"]:
                    os.replace(part, final)
                    fsync_dir(os.path.dirname(final))
                    ship_journal.append({"t": "ship_file", "f": name})
                    break
                attempts += 1
                try:
                    os.remove(part)
                except OSError:
                    pass
                ship_journal.append({"t": "ship_void", "f": name})
                off = 0
                if attempts > reships:
                    raise ShipError(
                        f"finalized copy of {name!r} failed its "
                        f"whole-file digest {attempts} time(s) — the "
                        "source is corrupt; refusing to restore from it"
                    )
            out["files"] += 1
        # every digest green, nothing durable says so yet: the crash
        # window the post_tail_verify kill point lands in — a retry
        # re-verifies the already-local files and pulls zero bytes
        _chaos("post_tail_verify")
        ship_journal.append({"t": "ship_done"})
    finally:
        ship_journal.close()
    _write_done_marker(dest)
    return out
