"""Wire framing + payload codecs for the fleet transport.

The frame format IS the journal's record format
(``har_tpu.serve.journal.encode_record``):

    u32 meta_len | u32 payload_len | u32 crc32(meta+payload)
    | meta (UTF-8 JSON) | payload (raw bytes)

reused deliberately: the payloads that cross the wire — session
exports, scored events, pushed samples — already exist as journal
records (``adopt``/``ack``/``push``), so one framing layer serves the
disk and the socket and the two cannot drift.  What the socket adds
over the disk is an adversarial peer: a frame can arrive torn (TCP
segmentation), corrupted, or absurdly sized, so ``FrameBuffer`` turns
CRC mismatch and oversized lengths into ``FrameError`` (a protocol
violation that kills the connection) instead of the journal reader's
silent torn-tail stop (which is the NORMAL end-of-log signature there).

Codecs mirror the journal record layouts:

  - exports (``encode_export``/``decode_export``): the ``adopt``
    record's shape — scalars + votes + monitor state in the JSON meta,
    ring float32 then EMA float64 concatenated in the payload;
  - events (``encode_events``/``decode_events``): the ``ack`` record's
    shape per event — decision fields in the meta list, probability
    vectors float64-concatenated in the payload;
  - samples (``encode_samples``/``decode_samples``): the ``push``
    record's shape — ``(n, channels)`` float32 rows in the payload.

Numeric fields round-trip through ``tobytes``/``frombuffer`` — exact,
so a migrated stream's bit-identity survives the wire by construction.
"""

from __future__ import annotations

import json
import zlib

import numpy as np

from har_tpu.serve.journal import _HDR, encode_record

# hard per-frame ceiling: the biggest legitimate frame is a push of a
# catch-up burst or a whole-partition poll response — megabytes, not
# gigabytes.  A length field past this is a corrupt or hostile peer and
# the connection dies rather than the allocator.
MAX_FRAME_BYTES = 32 << 20


class FrameError(RuntimeError):
    """Frame-level protocol violation: CRC mismatch, oversized length,
    or undecodable meta.  The connection that produced it is dead."""


def encode_frame(meta: dict, payload: bytes = b"") -> bytes:
    """One wire frame — exactly ``journal.encode_record`` plus the
    size ceiling (a frame we would refuse to read must never be sent)."""
    frame = encode_record(meta, payload)
    if len(frame) > MAX_FRAME_BYTES:
        raise FrameError(
            f"frame of {len(frame)} bytes exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES})"
        )
    return frame


class FrameBuffer:
    """Incremental frame decoder for a TCP byte stream.

    ``feed(chunk)`` appends received bytes; ``next_frame()`` returns
    the oldest complete ``(meta, payload)`` or None — torn frames
    simply wait for more bytes (TCP segmentation is not an error), but
    a CRC mismatch, an oversized length field or undecodable meta is a
    ``FrameError``: on a socket there is no "normal torn tail", only a
    peer that wrote garbage.
    """

    __slots__ = ("_buf", "_skip")

    def __init__(self):
        self._buf = bytearray()
        # bytes of a skipped frame still in flight: dropped at feed()
        # time so a refused payload never accumulates in the buffer
        self._skip = 0

    def feed(self, chunk: bytes) -> None:
        if self._skip:
            if len(chunk) <= self._skip:
                self._skip -= len(chunk)
                return
            chunk = memoryview(chunk)[self._skip :]
            self._skip = 0
        self._buf.extend(chunk)

    def __len__(self) -> int:
        return len(self._buf)

    def peek_header(self):
        """The edge-admission view: ``(meta, payload_len)`` as soon as
        the fixed header + meta bytes have arrived, WITHOUT waiting for
        (or touching) the payload.  This is what lets a gateway refuse
        a frame from its header alone — session count, byte length,
        staleness watermark all ride the meta — before any payload
        decode or allocation happens.  The CRC spans meta+payload and
        therefore cannot be checked yet; an admitted frame still goes
        through ``next_frame``'s full CRC verification, a refused one
        is discarded unverified (worst case a corrupt frame is refused
        as a shed instead of a FrameError — either way it never lands).
        Oversized declared lengths and garbled meta raise FrameError
        exactly like ``next_frame``."""
        buf = self._buf
        if len(buf) < _HDR.size:
            return None
        meta_len, payload_len, _crc = _HDR.unpack_from(buf, 0)
        total = _HDR.size + meta_len + payload_len
        if total > MAX_FRAME_BYTES:
            raise FrameError(
                f"declared frame of {total} bytes exceeds "
                f"MAX_FRAME_BYTES ({MAX_FRAME_BYTES})"
            )
        if len(buf) < _HDR.size + meta_len:
            return None
        try:
            meta = json.loads(
                bytes(buf[_HDR.size : _HDR.size + meta_len]).decode()
            )
        except (ValueError, UnicodeDecodeError) as exc:
            raise FrameError(f"undecodable frame meta: {exc}")
        return meta, payload_len

    def skip_frame(self) -> None:
        """Discard the frame at the head of the buffer without ever
        assembling its payload: bytes already buffered are deleted,
        bytes still in flight are dropped as ``feed`` receives them.
        Only valid after ``peek_header`` returned a header — a refused
        frame costs the edge its header parse, never an allocation."""
        buf = self._buf
        meta_len, payload_len, _crc = _HDR.unpack_from(buf, 0)
        total = _HDR.size + meta_len + payload_len
        have = min(len(buf), total)
        del buf[:have]
        self._skip += total - have

    def next_frame(self):
        buf = self._buf
        if len(buf) < _HDR.size:
            return None
        meta_len, payload_len, crc = _HDR.unpack_from(buf, 0)
        total = _HDR.size + meta_len + payload_len
        if total > MAX_FRAME_BYTES:
            raise FrameError(
                f"declared frame of {total} bytes exceeds "
                f"MAX_FRAME_BYTES ({MAX_FRAME_BYTES})"
            )
        if len(buf) < total:
            return None
        body = bytes(buf[_HDR.size : total])
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise FrameError("frame CRC mismatch")
        try:
            meta = json.loads(body[:meta_len].decode())
        except (ValueError, UnicodeDecodeError) as exc:
            raise FrameError(f"undecodable frame meta: {exc}")
        del buf[:total]
        return meta, body[meta_len:]


# --------------------------------------------------------------- codecs


def encode_samples(samples: np.ndarray) -> tuple[dict, bytes]:
    """The ``push`` record layout: float32 rows in the payload, row
    count in the meta (channels are fleet geometry, known both sides)."""
    arr = np.ascontiguousarray(samples, np.float32)
    return {"n": int(arr.shape[0]), "c": int(arr.shape[1])}, arr.tobytes()


def decode_samples(meta: dict, payload: bytes) -> np.ndarray:
    return np.frombuffer(payload, np.float32).reshape(
        int(meta["n"]), int(meta["c"])
    )


def encode_chunk_batch(items, offsets=None) -> tuple[dict, bytes]:
    """Multi-session push codec — one frame per delivery round instead
    of one RPC per session chunk: per-chunk ``{sid, n, c}`` dicts in
    the meta list (the ``push`` record's fields), the float32 sample
    rows concatenated in the payload in delivery order.  The meta's
    ``s`` (session count) and the frame's payload length are exactly
    what the gateway's edge admission reads from the header — a shed
    frame is refused before this payload is ever decoded.

    ``offsets`` (optional, parallel to ``items``) stamps each chunk
    with ``o``: the session-stream sample offset of the chunk's FIRST
    row.  The gateway compares ``o`` against the workers'
    ``watermark(sid)`` to drop already-delivered rows idempotently —
    the dedup that makes a client's post-reconnect re-send lossless
    instead of double-counted."""
    metas: list = []
    chunks: list = []
    for i, (sid, samples) in enumerate(items):
        arr = np.ascontiguousarray(samples, np.float32)
        em = {"sid": sid, "n": int(arr.shape[0]), "c": int(arr.shape[1])}
        if offsets is not None:
            em["o"] = int(offsets[i])
        metas.append(em)
        chunks.append(arr.tobytes())
    return {"chunks": metas, "s": len(metas)}, b"".join(chunks)


def decode_chunk_batch(meta: dict, payload: bytes) -> list:
    """Inverse of ``encode_chunk_batch``: ``[(sid, samples)]`` in
    delivery order.  The sample arrays are zero-copy ``frombuffer``
    views over the received payload — the only copy between the socket
    and the device is the engine's own staging write into its reserved
    ``StagingArena`` slot."""
    out = []
    pos = 0
    view = memoryview(payload)  # slices below are views, not copies
    for em in meta.get("chunks") or []:
        n, c = int(em["n"]), int(em["c"])
        nb = 4 * n * c
        out.append(
            (
                em["sid"],
                np.frombuffer(view[pos : pos + nb], np.float32).reshape(
                    n, c
                ),
            )
        )
        pos += nb
    return out


def encode_drift_reports(items) -> tuple[dict, bytes]:
    """Per-session DriftReport codec: the verdict scalars and the
    ``(generation, onset)`` episode id in the meta, the float64 z /
    log-ratio vectors concatenated in the payload — what ships the
    fleet-global retrain evidence across net workers
    (``NetCluster.observe_drift``).  float64 ``tobytes`` round-trip is
    exact, so the aggregator's thresholds and episode dedup see the
    same numbers on either side of the wire.  Sessions without a
    monitor (report ``None``) are skipped — same contract as
    ``RetrainTrigger.observe_server``."""
    metas: list = []
    chunks: list = []
    for sid, rep in items:
        if rep is None:
            continue
        z = np.ascontiguousarray(rep.location_z, np.float64)
        r = np.ascontiguousarray(rep.scale_log_ratio, np.float64)
        metas.append(
            {
                "sid": sid,
                "dr": bool(rep.drifting),
                "n": int(rep.n_samples),
                "on": None if rep.onset is None else int(rep.onset),
                "gen": int(rep.generation),
                "k": int(z.shape[0]),
            }
        )
        chunks.append(z.tobytes())
        chunks.append(r.tobytes())
    return {"reports": metas}, b"".join(chunks)


def decode_drift_reports(meta: dict, payload: bytes) -> list:
    """Inverse of ``encode_drift_reports``: ``[(sid, DriftReport)]``."""
    from har_tpu.monitoring import DriftReport

    out = []
    pos = 0
    for em in meta.get("reports") or []:
        k = int(em["k"])
        z = np.frombuffer(payload[pos : pos + 8 * k], np.float64)
        pos += 8 * k
        r = np.frombuffer(payload[pos : pos + 8 * k], np.float64)
        pos += 8 * k
        onset = em.get("on")
        out.append(
            (
                em["sid"],
                DriftReport(
                    drifting=bool(em["dr"]),
                    location_z=z,
                    scale_log_ratio=r,
                    n_samples=int(em["n"]),
                    onset=None if onset is None else int(onset),
                    generation=int(em.get("gen", 0)),
                ),
            )
        )
    return out


def encode_export(export: dict) -> tuple[dict, bytes]:
    """Session-export codec — the ``adopt`` journal record's layout:
    scalars/votes/monitor state in the meta, ring float32 then EMA
    float64 in the payload.  ``FleetServer.export_session`` output in,
    ``FleetServer.adopt_session`` input out the other side."""
    ring = np.ascontiguousarray(export["ring"], np.float32)
    ema = export.get("ema")
    payload = ring.tobytes()
    if ema is not None:
        payload += np.ascontiguousarray(ema, np.float64).tobytes()
    meta = {
        "sid": export["sid"],
        "w": int(ring.shape[0]),
        "c": int(ring.shape[1]),
        "n_seen": int(export["n_seen"]),
        "raw_seen": int(export["raw_seen"]),
        "next_emit": int(export["next_emit"]),
        "n_enqueued": int(export.get("n_enqueued", 0)),
        "n_scored": int(export.get("n_scored", 0)),
        "n_dropped": int(export.get("n_dropped", 0)),
        "handoffs": int(export.get("handoffs", 0)),
        "votes": [int(v) for v in export.get("votes") or []],
        "ema": ema is not None,
        "mon": export.get("monitor"),
    }
    return meta, payload


def decode_export(meta: dict, payload: bytes) -> dict:
    window, channels = int(meta["w"]), int(meta["c"])
    ring_bytes = window * channels * 4
    ema = None
    if meta.get("ema"):
        ema = np.frombuffer(payload[ring_bytes:], np.float64)
    return {
        "sid": meta["sid"],
        "ring": np.frombuffer(payload[:ring_bytes], np.float32).reshape(
            window, channels
        ),
        "n_seen": int(meta["n_seen"]),
        "raw_seen": int(meta["raw_seen"]),
        "next_emit": int(meta["next_emit"]),
        "n_enqueued": int(meta.get("n_enqueued", 0)),
        "n_scored": int(meta.get("n_scored", 0)),
        "n_dropped": int(meta.get("n_dropped", 0)),
        "handoffs": int(meta.get("handoffs", 0)),
        "votes": [int(v) for v in meta.get("votes") or []],
        "ema": ema,
        "monitor": meta.get("mon"),
    }


def encode_events(events: list) -> tuple[dict, bytes]:
    """FleetEvent-list codec — each event the ``ack`` record's shape:
    decision fields in the meta, the probability vector float64 in the
    payload.  Exact: the bit-identity pins compare
    ``probability.tobytes()`` and float64 round-trips unchanged.

    The engine types are imported lazily: the framing half of this
    module is also what the journal-ship agent (``net/ship.py``) rides,
    and an agent process streams journal bytes without ever needing the
    serving engine (or a jax backend) loaded."""
    metas = []
    chunks = []
    for fe in events:
        ev = fe.event
        prob = np.ascontiguousarray(ev.probability, np.float64)
        metas.append(
            {
                "sid": fe.session_id,
                "ti": int(ev.t_index),
                "lb": int(ev.label),
                "rl": int(ev.raw_label),
                "lat": float(ev.latency_ms),
                "dr": bool(ev.drift),
                "dm": None if ev.device_ms is None else float(ev.device_ms),
                "dg": bool(fe.degraded),
                "k": int(prob.shape[0]),
            }
        )
        chunks.append(prob.tobytes())
    return {"events": metas}, b"".join(chunks)


def decode_events(meta: dict, payload: bytes) -> list:
    from har_tpu.serve.engine import FleetEvent
    from har_tpu.serving import StreamEvent

    out = []
    pos = 0
    for em in meta.get("events") or []:
        k = int(em["k"])
        prob = np.frombuffer(payload[pos : pos + 8 * k], np.float64)
        pos += 8 * k
        out.append(
            FleetEvent(
                em["sid"],
                StreamEvent(
                    t_index=int(em["ti"]),
                    label=int(em["lb"]),
                    raw_label=int(em["rl"]),
                    probability=prob,
                    latency_ms=float(em["lat"]),
                    drift=bool(em["dr"]),
                    device_ms=em.get("dm"),
                ),
                degraded=bool(em.get("dg")),
            )
        )
    return out
