"""The release gate's wire checks + the bench lane measurements.

``wire_failover_smoke``: three REAL subprocess workers on loopback
TCP, one SIGKILLed mid-dispatch (an actual ``Process.kill`` — not a
shim), and the protocol must do the whole job on real clocks: refused
connections strike the prober, the lease expires, the partition
restores from its journal and migrates to the survivors over the
adopt RPC.  The verdict demands exactly-once delivery of every window
the un-killed schedule would have produced (``windows_lost == 0`` —
the expected count is deterministic), global conservation, and one
failover; the gate stamps ``{workers, transport, failover_ms,
windows_lost}`` into ``artifacts/test_gate.json``.

``wire_failover_benchmark`` is the same run instrumented per fleet
size for bench.py's ``wire_failover`` lane: failover wall time plus
the controller-side ``rpc_rtt`` p50/p99 — the comms term the
Spark-perf study (arXiv 1612.01437) says dominates once workers leave
shared memory, measured instead of assumed, against the in-process
``cluster_failover`` lane as the shared-memory baseline.

``wire_ingest_smoke``: the front-door pin — the SAME elastic traffic
trace driven twice, once against an in-process journaled FleetCluster
and once through real sockets (subprocess workers + the ingest
gateway's batched push frames), must produce bit-identical per-session
event streams at equal shed declarations, with conservation balanced
end-to-end and the group-committed ``acks`` records measured against
their per-event equivalent straight from the workers' journal
segments.  ``wire_ingest_benchmark`` is the bench lane: windows/s over
sockets vs in-process, the ack-path journal bytes per window, and the
coalescing ratio the PR's 0.5× acceptance bound rides on.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time

import numpy as np

from har_tpu.serve.cluster.membership import WorkerUnavailable
from har_tpu.serve.net.chaos import (
    _MATRIX_CHUNK_BYTES,
    _drive_net_cluster,
    _launch_private_fleet,
    _net_cluster_config,
    _safe_accounting,
    predicted_owner,
)
from har_tpu.serve.net.controller import NetCluster, launch_workers


def _run_wire_failover(
    sessions: int, workers: int, seed: int, n_samples: int,
    window: int = 100, hop: int = 50, private: bool = False,
    replicated: bool = False,
) -> dict:
    """One measured wire-failover run: drive, kill the victim process
    once windows are flowing, let the protocol finish, verdict.

    ``private=True`` is the SHARED-NOTHING variant: every worker's
    journal lives in its own per-host directory the controller never
    reads, and the dead partition arrives via the journal-shipping RPC
    from the host's agent (``har_tpu.serve.net.ship``) — the
    ``journal_ship_smoke`` / bench-lane configuration.  ``False``
    keeps the single-box shared-disk restore, which doubles as the
    bench lane's baseline.

    ``replicated=True`` (implies private) registers a warm standby
    that tail-follows every worker's agent from the controller's poll
    loop (``har_tpu.serve.replica``): the kill must then fail over
    from the standby's already-verified local bytes — the verdict
    additionally demands ``failover_path_bytes == 0`` (zero journal
    bytes moved AFTER the death) and at least one standby-sourced
    fetch."""
    from har_tpu.serve.chaos import _recordings
    from har_tpu.serve.loadgen import AnalyticDemoModel

    private = private or replicated
    model = AnalyticDemoModel()
    victim = predicted_owner(0, workers)
    root = tempfile.mkdtemp(prefix="har_wire_smoke_")
    priv = tempfile.mkdtemp(prefix="har_wire_priv_")
    procs: dict = {}
    agent_procs: dict = {}
    try:
        if private:
            net_workers, handles = _launch_private_fleet(
                root, priv, workers, window=window, hop=hop,
                target_batch=32, max_delay_ms=0.0,
            )
            agent_procs = {
                wid: h.process for wid, h in handles.items()
            }
            agents = {
                wid: h.client() for wid, h in handles.items()
            }
        else:
            net_workers = launch_workers(
                root, workers, window=window, hop=hop,
                target_batch=32, max_delay_ms=0.0,
            )
            agents = None
        procs = {w.worker_id: w.process for w in net_workers}
        cluster = NetCluster(
            model, root, _workers=net_workers,
            config=_net_cluster_config(),
            loader=lambda ver: model,
            agents=agents,
            ship_chunk_bytes=_MATRIX_CHUNK_BYTES,
        )
        for i in range(sessions):
            cluster.add_session(i)
        if replicated:
            from har_tpu.serve.net.controller import REPLICA_DIR
            from har_tpu.serve.replica import StandbyAgent

            # in-controller standby over the agents' ship RPCs; its
            # transfer counters land on the cluster's net_stats so the
            # steady-state tail traffic is measured alongside the rest
            cluster.register_standby(
                StandbyAgent(
                    os.path.join(root, REPLICA_DIR),
                    {wid: h.client() for wid, h in handles.items()},
                    loader=lambda ver: model,
                    chunk_bytes=_MATRIX_CHUNK_BYTES,
                    stats=cluster.net_stats,
                )
            )
        recordings = _recordings(sessions, n_samples, 3, seed)
        events: list = []
        balance_log: list = []
        killed = {"t": None}
        lag = {"last": 0, "at_kill": None}

        def on_round(c):
            if replicated:
                lag["last"] = sum(
                    c.net_stats.replication_lag_records.values()
                )
            if killed["t"] is None:
                try:
                    scored = c.accounting()["scored"]
                except WorkerUnavailable:
                    return
                if scored > 0:
                    procs[victim].kill()  # a real SIGKILL
                    killed["t"] = time.perf_counter()
                    lag["at_kill"] = lag["last"]
                return
            _safe_accounting(c, balance_log)

        _drive_net_cluster(
            cluster, recordings, [0] * sessions, n_samples, hop,
            events, on_round,
        )
        wall_failover_ms = (
            None
            if killed["t"] is None
            else (time.perf_counter() - killed["t"]) * 1e3
        )
        stats = cluster.cluster_stats()
        acct = stats["accounting"]
        keys = {(e.session_id, e.event.t_index) for e in events}
        expected = sessions * ((n_samples - window) // hop + 1)
        why = None
        if killed["t"] is None:
            why = "the victim was never killed (no windows scored?)"
        elif len(keys) != len(events):
            why = "an event was delivered twice across the kill"
        elif len(keys) != expected:
            why = f"{expected - len(keys)} window(s) lost"
        elif not acct["balanced"] or acct["pending"] != 0:
            why = f"conservation violated: {acct}"
        elif stats["failovers"] != 1:
            why = f"failovers == {stats['failovers']}, expected 1"
        elif any(not s["balanced"] for s in balance_log):
            why = "conservation violated in a per-round snapshot"
        rpc = cluster.transport_stats()
        if why is None and private and rpc["shipped_bytes"] <= 0:
            why = (
                "failover completed without shipping any journal "
                "bytes — the shared-nothing path was bypassed"
            )
        if why is None and replicated:
            if rpc["standby_fetches"] < 1:
                why = (
                    "failover never sourced the partition from the "
                    "warm standby"
                )
            elif rpc["failover_path_bytes"] != 0:
                why = (
                    f"warm failover moved {rpc['failover_path_bytes']} "
                    "journal byte(s) after the death; a caught-up "
                    "standby must transfer zero"
                )
        out = {
            "ok": why is None,
            "why": why,
            "sessions": int(sessions),
            # the LAUNCHED fleet size (the bench lane's semantics for
            # this key); the post-failover census rides alongside
            "workers": int(workers),
            "surviving_workers": stats["workers"],
            "transport": "tcp",
            "failovers": stats["failovers"],
            "migrated_sessions": max(
                stats["migrated_sessions"], stats["migrations"]
            ),
            # restore + drain + hand-offs (the control plane's own
            # work), and the wall time from the SIGKILL to the drive
            # settling — detection latency included
            "failover_ms": round(stats["failover_ms"], 3),
            "detect_to_settle_ms": (
                None
                if wall_failover_ms is None
                else round(wall_failover_ms, 1)
            ),
            "windows_lost": max(expected - len(keys), 0),
            "private_dirs": bool(private),
            "replicated": bool(replicated),
            "ship_ms": rpc["ship_ms"],
            "failover_path_bytes": rpc["failover_path_bytes"],
            "standby_fetches": rpc["standby_fetches"],
            "standbys": rpc["standbys"],
            "steady_lag_records": int(lag["last"]),
            "lag_records_at_kill": (
                None if lag["at_kill"] is None else int(lag["at_kill"])
            ),
            "rpc": rpc,
        }
        cluster.shutdown_workers()
        cluster.close()
        return out
    finally:
        # a failed run must not leak worker/agent processes, and the
        # rmtree must never delete the root under live writers (clean
        # exits already reaped: kill is a no-op on an exited process)
        for proc in list(procs.values()) + list(agent_procs.values()):
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)
        shutil.rmtree(priv, ignore_errors=True)


def wire_failover_smoke(
    sessions: int = 18, workers: int = 3, seed: int = 0
) -> dict:
    """Gate verdict: one wire failover run reshaped into the gate-log
    stamp (keys pinned by tests/test_release_gate.py)."""
    out = _run_wire_failover(sessions, workers, seed, n_samples=300)
    return {
        "ok": out["ok"],
        "why": out["why"],
        "sessions": out["sessions"],
        "workers": out["workers"],
        "transport": out["transport"],
        "failover_ms": out["failover_ms"],
        "windows_lost": out["windows_lost"],
        "rpc_rtt_p50_ms": out["rpc"]["rpc_rtt_p50_ms"],
        "rpc_retries": out["rpc"]["rpc_retries"],
    }


def journal_ship_smoke(
    sessions: int = 18, workers: int = 3, seed: int = 0
) -> dict:
    """Gate verdict for SHARED-NOTHING failover (the journal-shipping
    tentpole): three subprocess workers with PRIVATE journal
    directories (one per-host dir each, a ship agent beside it — the
    controller never reads a worker's filesystem), one worker
    SIGKILLed mid-dispatch, and the dead partition must arrive over
    the ship RPC — chunked, digest-verified, restored from the staged
    copy — before its sessions migrate to the survivors.  The stamp
    carries ``{shipped_bytes, chunks, resumes, windows_lost}`` (keys
    pinned by tests/test_release_gate.py)."""
    out = _run_wire_failover(
        sessions, workers, seed, n_samples=300, private=True
    )
    return {
        "ok": out["ok"],
        "why": out["why"],
        "sessions": out["sessions"],
        "workers": out["workers"],
        "transport": out["transport"],
        "private_dirs": out["private_dirs"],
        "shipped_bytes": out["rpc"]["shipped_bytes"],
        "chunks": out["rpc"]["ship_chunks"],
        "resumes": out["rpc"]["ship_resumes"],
        "ship_ms": out["rpc"]["ship_ms"],
        "failover_ms": out["failover_ms"],
        "windows_lost": out["windows_lost"],
    }


def replication_smoke(
    sessions: int = 18, workers: int = 3, seed: int = 0
) -> dict:
    """Gate verdict for CONTINUOUS REPLICATION (the warm-standby
    tentpole): the journal-ship fleet with one standby tail-following
    every worker's agent, one worker SIGKILLed mid-dispatch — and the
    failover must come from the standby's already-local, already-
    verified bytes: ``failover_path_bytes == 0`` (the ship leaves the
    failover path entirely), with the same exactly-once + conservation
    verdict as every other wire smoke.  The stamp carries ``{standbys,
    lag_records_at_kill, failover_path_bytes, failover_ms,
    windows_lost}`` (keys pinned by tests/test_release_gate.py)."""
    out = _run_wire_failover(
        sessions, workers, seed, n_samples=300, replicated=True
    )
    return {
        "ok": out["ok"],
        "why": out["why"],
        "sessions": out["sessions"],
        "workers": out["workers"],
        "transport": out["transport"],
        "standbys": out["standbys"],
        "standby_fetches": out["standby_fetches"],
        "lag_records_at_kill": out["lag_records_at_kill"],
        "failover_path_bytes": out["failover_path_bytes"],
        "failover_ms": out["failover_ms"],
        "windows_lost": out["windows_lost"],
    }


def journal_ship_benchmark(
    session_counts,
    n_runs: int = 3,
    *,
    workers: int = 3,
    seed: int = 0,
    n_samples: int = 300,
) -> list[dict]:
    """bench.py's ``journal_ship`` lane rows: per fleet size, the
    shared-nothing failover measured twice — the SHIPPED run (private
    dirs + agents: ``ship_ms`` inside fetch_journal, plus the whole
    failover wall time) against the SHARED-DIR baseline (the same
    kill, the dead directory restored in place) — so the cost of
    crossing the process boundary with the recovery currency is a
    measured delta, not an assumption.  ``contract_ok`` pins the
    exactly-once + complete-delivery + conservation verdict on every
    measured run of ALL modes.

    The REPLICATED arm rides in the same lane: the identical kill
    with a warm standby tailing every worker, where the failover path
    moves zero journal bytes (``replicated_failover_path_bytes``) —
    its ``replicated_failover_ms_median`` against ``failover_ms_median``
    is the headline number continuous replication buys."""
    rows = []
    for n_sessions in session_counts:
        ship_ms, failover_ms, base_ms, repl_ms = [], [], [], []
        shipped_bytes, chunks, ok = 0, 0, True
        repl_path_bytes, repl_lag = 0, 0
        for r in range(int(n_runs)):
            shipped = _run_wire_failover(
                int(n_sessions), workers, seed + r, n_samples,
                private=True,
            )
            base = _run_wire_failover(
                int(n_sessions), workers, seed + r, n_samples,
                private=False,
            )
            repl = _run_wire_failover(
                int(n_sessions), workers, seed + r, n_samples,
                replicated=True,
            )
            ok = ok and shipped["ok"] and base["ok"] and repl["ok"]
            ship_ms.append(shipped["rpc"]["ship_ms"])
            failover_ms.append(shipped["failover_ms"])
            base_ms.append(base["failover_ms"])
            repl_ms.append(repl["failover_ms"])
            shipped_bytes = shipped["rpc"]["shipped_bytes"]
            chunks = shipped["rpc"]["ship_chunks"]
            repl_path_bytes = repl["failover_path_bytes"]
            repl_lag = repl["steady_lag_records"]
        rows.append(
            {
                "n_sessions": int(n_sessions),
                "workers": int(workers),
                "transport": "tcp",
                "ship_ms_median": round(float(np.median(ship_ms)), 3),
                "ship_ms_std": round(float(np.std(ship_ms)), 3),
                "failover_ms_median": round(
                    float(np.median(failover_ms)), 3
                ),
                "baseline_failover_ms_median": round(
                    float(np.median(base_ms)), 3
                ),
                "replicated_failover_ms_median": round(
                    float(np.median(repl_ms)), 3
                ),
                "replicated_failover_path_bytes": int(repl_path_bytes),
                "replicated_steady_lag_records": int(repl_lag),
                "shipped_bytes": int(shipped_bytes),
                "chunks": int(chunks),
                "contract_ok": ok,
            }
        )
    return rows


def wire_failover_benchmark(
    session_counts,
    n_runs: int = 3,
    *,
    workers: int = 3,
    seed: int = 0,
    n_samples: int = 300,
) -> list[dict]:
    """bench.py's ``wire_failover`` lane rows: per fleet size, median
    failover wall time over the REAL transport plus the rpc_rtt
    distribution, ``contract_ok`` pinning the conservation + complete-
    delivery verdict on every measured run."""
    rows = []
    for n_sessions in session_counts:
        times, rtt50, rtt99, migrated, ok = [], [], [], 0, True
        for r in range(int(n_runs)):
            out = _run_wire_failover(
                int(n_sessions), workers, seed + r, n_samples
            )
            ok = ok and out["ok"]
            times.append(out["failover_ms"])
            if out["rpc"]["rpc_rtt_p50_ms"] is not None:
                rtt50.append(out["rpc"]["rpc_rtt_p50_ms"])
                rtt99.append(out["rpc"]["rpc_rtt_p99_ms"])
            migrated = out["migrated_sessions"]
        rows.append(
            {
                "n_sessions": int(n_sessions),
                "workers": int(workers),
                "transport": "tcp",
                "migrated_sessions": int(migrated),
                "failover_ms_median": round(float(np.median(times)), 3),
                "failover_ms_std": round(float(np.std(times)), 3),
                "rpc_rtt_p50_ms": (
                    round(float(np.median(rtt50)), 4) if rtt50 else None
                ),
                "rpc_rtt_p99_ms": (
                    round(float(np.median(rtt99)), 4) if rtt99 else None
                ),
                "contract_ok": ok,
            }
        )
    return rows


# ------------------------------------------------- wire-rate ingest


def _ack_journal_stats(journal_dirs) -> dict:
    """Measure the ack path's journal cost straight from the workers'
    segments: the actual bytes of the group-committed ``acks`` records
    vs the bytes the SAME entries would have cost as per-event ``ack``
    records — each entry reconstructed (sid, a per-session running
    window counter as its t_index, version, shed, its own float64 probs
    row) and re-encoded through the journal's own framing
    (``encode_record``), so the coalescing ratio is a measurement of
    both layouts under one encoder, not a model."""
    from har_tpu.serve.journal import encode_record, read_segment

    acks_records = entries = legacy_ack_records = 0
    coalesced_bytes = equiv_bytes = 0
    next_ti: dict = {}
    for jdir in journal_dirs:
        try:
            names = sorted(os.listdir(jdir))
        except OSError:
            continue
        for name in names:
            if not (name.startswith("wal.") and name.endswith(".log")):
                continue
            records, _torn = read_segment(os.path.join(jdir, name))
            for meta, payload in records:
                t = meta.get("t")
                if t == "ack":
                    legacy_ack_records += 1
                elif t == "acks":
                    n = int(meta["n"])
                    acks_records += 1
                    entries += n
                    coalesced_bytes += len(encode_record(meta, payload))
                    rows = np.frombuffer(payload, np.float64).reshape(
                        n, -1
                    )
                    for sid, row in zip(meta["sids"], rows):
                        ti = next_ti.get(sid, 0)
                        next_ti[sid] = ti + 1
                        equiv_bytes += len(
                            encode_record(
                                {
                                    "t": "ack",
                                    "sid": sid,
                                    "ti": ti,
                                    "ver": meta.get("ver", "A"),
                                    "shed": bool(meta.get("shed")),
                                },
                                row.tobytes(),
                            )
                        )
    return {
        "acks_records": acks_records,
        "entries": entries,
        "legacy_ack_records": legacy_ack_records,
        "coalesced_bytes": coalesced_bytes,
        "per_record_bytes": equiv_bytes,
        "bytes_per_window": (
            round(coalesced_bytes / entries, 2) if entries else None
        ),
        "per_record_bytes_per_window": (
            round(equiv_bytes / entries, 2) if entries else None
        ),
        "coalesce_ratio": (
            round(coalesced_bytes / equiv_bytes, 4)
            if equiv_bytes
            else None
        ),
    }


def _by_session(events) -> dict:
    from har_tpu.serve.chaos import _event_fields

    out: dict = {}
    for fe in events:
        out.setdefault(fe.session_id, []).append(_event_fields(fe))
    return out


def _run_wire_ingest(
    peak_sessions: int,
    workers: int,
    seed: int,
    *,
    rounds: int = 40,
    window: int = 100,
    hop: int = 50,
    target_batch: int = 32,
) -> dict:
    """One measured front-door run: the same elastic traffic trace
    driven against (a) an in-process journaled FleetCluster — the
    reference — and (b) subprocess workers behind the ingest gateway
    over real sockets, batched push frames and all.  The verdict pins
    bit-identical per-session event streams at equal shed declarations,
    conservation balanced at the edge (every client window enqueued
    lands in fleet accounting; refusals are declared receipts), and
    zero undeclared drops."""
    from har_tpu.serve.cluster.controller import FleetCluster
    from har_tpu.serve.engine import FleetConfig
    from har_tpu.serve.journal import JournalConfig
    from har_tpu.serve.loadgen import AnalyticDemoModel
    from har_tpu.serve.net.gateway import GatewayClient, launch_gateway
    from har_tpu.serve.traffic import TraceSpec, TrafficTrace, drive_trace

    spec = TraceSpec(
        kind="diurnal",
        peak_sessions=peak_sessions,
        swing=4.0,
        rounds=rounds,
        period=rounds,
        seed=seed,
    )
    trace = TrafficTrace(spec)
    fleet_config = FleetConfig(
        target_batch=target_batch, max_delay_ms=0.0, retries=1
    )
    # snapshot_every=0: only the attach-time snapshot, so every ack
    # record of the run survives in the wal segments for measurement
    journal_config = JournalConfig(flush_every=512, snapshot_every=0)

    # ---- reference: the same trace, in-process, journaled workers
    ref_root = tempfile.mkdtemp(prefix="har_ingest_ref_")
    ref_events: list = []
    try:
        ref_cluster = FleetCluster(
            AnalyticDemoModel(),
            ref_root,
            workers=workers,
            window=window,
            hop=hop,
            fleet_config=fleet_config,
            journal_config=journal_config,
        )
        ref_events, ref_report = drive_trace(ref_cluster, trace)
        ref_acct = ref_cluster.accounting()
        for w in ref_cluster._workers.values():
            w.close()
    finally:
        shutil.rmtree(ref_root, ignore_errors=True)

    # ---- the wire run: subprocess workers + gateway + batched frames
    root = tempfile.mkdtemp(prefix="har_ingest_wire_")
    procs: list = []
    client = None
    try:
        net_workers = launch_workers(
            root,
            workers,
            window=window,
            hop=hop,
            target_batch=target_batch,
            max_delay_ms=0.0,
            flush_every=512,
            snapshot_every=0,
        )
        procs = [w.process for w in net_workers]
        gw_proc, gw_host, gw_port = launch_gateway(root, net_workers)
        procs.append(gw_proc)
        client = GatewayClient(gw_host, gw_port)
        wire_events, wire_report = drive_trace(
            client, TrafficTrace.from_spec(trace.spec())
        )
        wire_acct = client.accounting()
        gw_stats = client.gateway_stats()
        # orderly teardown so every journal byte is on disk before the
        # segment scan: gateway first, then the workers close their
        # journals via the shutdown RPC
        client.shutdown()
        client.close()
        client = None
        gw_proc.wait(timeout=30)
        jdirs = []
        for w in net_workers:
            jdirs.append(w.journal_dir)
            w.shutdown()
            w.close()
            w.process.wait(timeout=30)
        ack_stats = _ack_journal_stats(jdirs)

        # ---- verdict
        ref_by = _by_session(ref_events)
        wire_by = _by_session(wire_events)
        keys = {(fe.session_id, fe.event.t_index) for fe in wire_events}
        windows_lost = len(ref_events) - len(wire_events)
        why = None
        if len(keys) != len(wire_events):
            why = "an event was delivered twice through the gateway"
        elif wire_by != ref_by:
            if windows_lost > 0:
                why = f"{windows_lost} window(s) lost at the front door"
            else:
                why = (
                    "wire events are not bit-identical to the "
                    "in-process run"
                )
        elif client_sheds_differ(gw_stats, wire_report):
            why = "edge sheds were not declared symmetrically"
        elif not wire_acct["balanced"] or wire_acct["pending"] != 0:
            why = f"conservation violated over the wire: {wire_acct}"
        elif wire_acct["dropped"] != ref_acct["dropped"]:
            why = (
                "shed declarations diverged: wire dropped "
                f"{wire_acct['dropped']}, in-process "
                f"{ref_acct['dropped']}"
            )
        elif wire_acct["enqueued"] != ref_acct["enqueued"]:
            why = (
                "an undeclared drop at the edge: wire enqueued "
                f"{wire_acct['enqueued']}, in-process "
                f"{ref_acct['enqueued']}"
            )
        elif not ack_stats["entries"]:
            why = "no group-committed acks records reached the journal"
        return {
            "ok": why is None,
            "why": why,
            "sessions": int(trace.total_sessions),
            "workers": int(workers),
            "transport": "tcp",
            "rounds": int(rounds),
            "frames": int(gw_stats["admitted_frames"]),
            "shed_frames": int(gw_stats["shed_frames"]),
            "windows_lost": max(windows_lost, 0),
            "windows_enqueued": int(wire_acct["enqueued"]),
            "windows_scored": int(wire_acct["scored"]),
            "wire_duration_s": wire_report.duration_s,
            "inproc_duration_s": ref_report.duration_s,
            "event_latency_ms": [
                float(fe.event.latency_ms) for fe in wire_events
            ],
            "ack_stats": ack_stats,
        }
    finally:
        if client is not None:
            client.close()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)


def client_sheds_differ(gw_stats: dict, wire_report) -> bool:
    """The edge's declared-receipt law, checked from both ends: every
    frame the gateway refused must be a shed the CLIENT also counted —
    here the honest drive sends no stale/oversized frames, so both
    sides must agree on zero."""
    return int(gw_stats["shed_frames"]) != 0


def wire_ingest_smoke(
    peak_sessions: int = 64, workers: int = 2, seed: int = 0
) -> dict:
    """Gate verdict: one front-door run reshaped into the gate-log
    stamp (keys pinned by tests/test_release_gate.py)."""
    out = _run_wire_ingest(peak_sessions, workers, seed)
    ack = out["ack_stats"]
    return {
        "ok": out["ok"],
        "why": out["why"],
        "sessions": out["sessions"],
        "workers": out["workers"],
        "transport": out["transport"],
        "frames": out["frames"],
        "bytes_per_window": ack["bytes_per_window"],
        "per_record_bytes_per_window": ack[
            "per_record_bytes_per_window"
        ],
        "ack_coalesce_ratio": ack["coalesce_ratio"],
        "ack_records_coalesced": ack["entries"],
        "windows_lost": out["windows_lost"],
    }


def wire_ingest_benchmark(
    session_counts,
    n_runs: int = 3,
    *,
    workers: int = 2,
    seed: int = 0,
    rounds: int = 40,
) -> list[dict]:
    """bench.py's ``wire_ingest`` lane rows: per traffic size, the
    front-door throughput over real sockets (median windows/s of
    ``n_runs``) against the in-process drive of the SAME trace, the
    per-event p99 latency over the wire, and the ack path's journal
    bytes per window — coalesced vs the per-event equivalent, with the
    ratio the 0.5× acceptance bound rides on.  ``contract_ok`` pins
    the bit-identity + conservation verdict on every measured run."""
    rows = []
    for n_sessions in session_counts:
        wire_ws, inproc_ws, p99s = [], [], []
        ack = {}
        frames, ok = 0, True
        for r in range(int(n_runs)):
            out = _run_wire_ingest(
                int(n_sessions), workers, seed + r, rounds=rounds
            )
            ok = ok and out["ok"]
            scored = out["windows_scored"]
            if out["wire_duration_s"]:
                wire_ws.append(scored / out["wire_duration_s"])
            if out["inproc_duration_s"]:
                inproc_ws.append(scored / out["inproc_duration_s"])
            lat = out["event_latency_ms"]
            if lat:
                p99s.append(float(np.percentile(lat, 99)))
            ack = out["ack_stats"]
            frames = out["frames"]
        rows.append(
            {
                "n_sessions": int(n_sessions),
                "workers": int(workers),
                "transport": "tcp",
                "frames": int(frames),
                "windows_s_median": round(float(np.median(wire_ws)), 1),
                "windows_s_std": round(float(np.std(wire_ws)), 1),
                "inproc_windows_s_median": round(
                    float(np.median(inproc_ws)), 1
                ),
                "event_p99_ms": (
                    round(float(np.median(p99s)), 3) if p99s else None
                ),
                "ack_bytes_per_window": ack.get("bytes_per_window"),
                "per_record_bytes_per_window": ack.get(
                    "per_record_bytes_per_window"
                ),
                "ack_coalesce_ratio": ack.get("coalesce_ratio"),
                "contract_ok": ok,
            }
        )
    return rows


def _run_gateway_ha(
    sessions: int,
    workers: int,
    seed: int,
    *,
    rounds: int = 12,
    window: int = 100,
    hop: int = 50,
    lease_s: float = 1.0,
    kill_round: int | None = None,
) -> dict:
    """One measured HA front-door run: an elected gateway PAIR fronting
    subprocess workers, two tenant cohorts (``care`` weight 3.0 — the
    protected monitored-patient streams — and ``bulk`` weight 1.0)
    pushing through reconnecting HA clients, and the ACTIVE gateway
    SIGKILLed mid-run.  The verdict pins the lease flip losslessly:
    every client reconnects and resumes from the workers' watermarks,
    ``windows_lost == 0``, the combined scored stream bit-identical to
    an in-process un-killed run of the same schedule — then a one-
    tenant storm (an oversized ``bulk`` burst) is refused with a
    declared receipt while the ``care`` cohort sees ZERO edge sheds,
    and the edge ledger's per-tenant slices sum to its globals."""
    from har_tpu.serve.chaos import _recordings
    from har_tpu.serve.cluster.controller import FleetCluster
    from har_tpu.serve.engine import FleetConfig
    from har_tpu.serve.journal import JournalConfig
    from har_tpu.serve.loadgen import AnalyticDemoModel
    from har_tpu.serve.net.client import HAGatewayClient
    from har_tpu.serve.net.gateway import launch_gateway_pair
    from har_tpu.serve.net.ingest import IngestConfig
    from har_tpu.utils.backoff import BackoffPolicy

    sessions = max(int(sessions), 2)
    if kill_round is None:
        kill_round = max(rounds // 3, 1)
    n_samples = rounds * hop
    recordings = _recordings(sessions, n_samples, 3, seed)
    care_sids = list(range(sessions // 2))
    bulk_sids = list(range(sessions // 2, sessions))
    config = IngestConfig(
        # a soft byte ceiling the storm burst overflows while every
        # honest frame stays far below it
        max_frame_bytes=1 << 18,
        tenants=(("bulk", 1.0), ("care", 3.0)),
    )

    # ---- reference: the same schedule, in-process, un-killed --------
    ref_root = tempfile.mkdtemp(prefix="har_gwha_ref_")
    ref_events: list = []
    try:
        ref = FleetCluster(
            AnalyticDemoModel(),
            ref_root,
            workers=workers,
            window=window,
            hop=hop,
            fleet_config=FleetConfig(
                target_batch=32, max_delay_ms=0.0, retries=1
            ),
            journal_config=JournalConfig(
                flush_every=512, snapshot_every=40
            ),
        )
        for i in range(sessions):
            ref.add_session(i)
        for r in range(rounds):
            for i in range(sessions):
                ref.push(i, recordings[i][r * hop:(r + 1) * hop])
            ref_events.extend(ref.poll(force=True))
        ref_events.extend(ref.flush())
        for w in ref._workers.values():
            w.close()
    finally:
        shutil.rmtree(ref_root, ignore_errors=True)

    # ---- the wire run: worker fleet + elected pair + two tenants ----
    root = tempfile.mkdtemp(prefix="har_gwha_wire_")
    procs: list = []
    clients: list = []
    try:
        net_workers = launch_workers(
            root, workers, window=window, hop=hop, target_batch=32,
            max_delay_ms=0.0, flush_every=512, snapshot_every=40,
        )
        procs = [w.process for w in net_workers]
        pair = launch_gateway_pair(
            root, net_workers, config=config, lease_s=lease_s
        )
        procs.extend(p for p, _, _ in pair)
        addrs = [f"{h}:{p}" for _, h, p in pair]
        policy = BackoffPolicy(
            base_ms=20.0, cap_ms=250.0, factor=2.0, jitter=0.25
        )
        care = HAGatewayClient(
            addrs, tenant="care", deadline_s=2.0, retries=1,
            reconnect=policy, seed=seed,
        )
        bulk = HAGatewayClient(
            addrs, tenant="bulk", deadline_s=2.0, retries=1,
            reconnect=policy, seed=seed + 1,
        )
        clients = [care, bulk]
        for i in care_sids:
            care.add_session(i)
        for i in bulk_sids:
            bulk.add_session(i)
        events: list = []
        t_kill = None
        for r in range(rounds):
            if r == kill_round:
                # a real SIGKILL of the ACTIVE gateway, client frames
                # in flight on both tenants
                pair[0][0].kill()
                t_kill = time.monotonic()
            for i in care_sids:
                care.push(i, recordings[i][r * hop:(r + 1) * hop])
            for i in bulk_sids:
                bulk.push(i, recordings[i][r * hop:(r + 1) * hop])
            events.extend(care.poll(force=True))
            events.extend(bulk.poll(force=True))
        events.extend(care.flush())
        events.extend(bulk.flush())

        # ---- the one-tenant storm: an oversized bulk burst ----------
        storm_sid = sessions
        bulk.add_session(storm_sid)
        bulk.push(
            storm_sid, np.zeros((24576, 3), np.float32)
        )  # 288 KiB > the 256 KiB soft ceiling: shed, with a receipt
        events.extend(bulk.poll(force=True))

        acct = care.accounting()
        gw = care.gateway_stats()
        failover_s = time.monotonic() - (t_kill or time.monotonic())

        # ---- verdict ------------------------------------------------
        ref_by = _by_session(ref_events)
        got_by = _by_session(events)
        keys = {(fe.session_id, fe.event.t_index) for fe in events}
        windows_lost = len(ref_events) - len(events)
        slices = gw.get("tenants", {})
        why = None
        if len(keys) != len(events):
            why = "an event was delivered twice across the lease flip"
        elif windows_lost != 0:
            why = f"{windows_lost} window(s) lost across the lease flip"
        elif got_by != ref_by:
            why = (
                "scored stream not bit-identical to the un-killed "
                "in-process run"
            )
        elif care.edge_sheds != 0:
            why = (
                f"the protected tenant took {care.edge_sheds} edge "
                "shed(s) during the bulk storm"
            )
        elif bulk.shed_by_reason.get("frame_bytes", 0) < 1:
            why = "the bulk storm was not refused at the edge"
        elif slices.get("care", {}).get("shed_frames", 0) != 0:
            why = "the edge ledger charged sheds to the care slice"
        elif slices.get("bulk", {}).get("shed_frames", 0) < 1:
            why = "the edge ledger missed the bulk storm shed"
        elif any(
            sum(s.get(k, 0) for s in slices.values()) != gw.get(k)
            for k in (
                "admitted_frames", "admitted_sessions",
                "admitted_bytes", "shed_frames", "shed_sessions",
                "shed_bytes",
            )
        ):
            why = (
                "per-tenant slices do not sum to the edge ledger "
                "globals"
            )
        elif not acct["balanced"] or acct["pending"] != 0:
            why = f"conservation violated across the flip: {acct}"
        elif min(care.gen, bulk.gen) < 2:
            why = (
                "a client never saw the fenced generation move "
                f"(care={care.gen}, bulk={bulk.gen})"
            )
        elif min(care.failover_episodes, bulk.failover_episodes) < 1:
            why = "a client recorded no failover episode"
        failover_ms = max(
            care.last_failover_ms or 0.0, bulk.last_failover_ms or 0.0
        )
        out = {
            "ok": why is None,
            "why": why,
            "sessions": int(sessions),
            "workers": int(workers),
            "gateways": 2,
            "transport": "tcp",
            "rounds": int(rounds),
            "windows_lost": windows_lost,
            "delivered": len(events),
            "failover_ms": float(failover_ms),
            "run_failover_s": float(failover_s),
            "reconnects": care.reconnects + bulk.reconnects,
            "moved_receipts": care.moved_receipts + bulk.moved_receipts,
            "resumed_sessions": len(care.resumed | bulk.resumed),
            "tenant_sheds": {
                t: int(s.get("shed_frames", 0))
                for t, s in slices.items()
            },
            "lease_gen": int(max(care.gen, bulk.gen)),
            "accounting": acct,
        }
        care.shutdown()
        return out
    finally:
        for c in clients:
            c.close()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)


def gateway_ha_smoke(
    sessions: int = 8, workers: int = 2, seed: int = 0
) -> dict:
    """Gate verdict: one gateway-pair failover run reshaped into the
    gate-log stamp (keys pinned by tests/test_release_gate.py)."""
    out = _run_gateway_ha(sessions, workers, seed)
    return {
        "ok": out["ok"],
        "why": out["why"],
        "sessions": out["sessions"],
        "workers": out["workers"],
        "gateways": out["gateways"],
        "transport": out["transport"],
        "failover_ms": out["failover_ms"],
        "resumed_sessions": out["resumed_sessions"],
        "tenant_sheds": out["tenant_sheds"],
        "windows_lost": out["windows_lost"],
    }


def gateway_ha_benchmark(
    session_counts,
    n_runs: int = 3,
    *,
    workers: int = 2,
    seed: int = 0,
    rounds: int = 12,
) -> list[dict]:
    """bench.py's ``gateway_ha`` lane rows: per session count, the
    failover cost of the ACTIVE gateway dying — wall time from the
    SIGKILL to the first frame the new leader ACCEPTS
    (``failover_ms``, median of ``n_runs``) — plus the reconnect storm
    size.  ``contract_ok`` pins the lossless verdict (bit-identity,
    zero windows lost, tenant fairness) on every measured run."""
    rows = []
    for n_sessions in session_counts:
        fo_ms, reconnects, moved = [], 0, 0
        resumed, ok = 0, True
        for r in range(int(n_runs)):
            out = _run_gateway_ha(
                int(n_sessions), workers, seed + r, rounds=rounds
            )
            ok = ok and out["ok"]
            fo_ms.append(out["failover_ms"])
            reconnects = out["reconnects"]
            moved = out["moved_receipts"]
            resumed = out["resumed_sessions"]
        rows.append(
            {
                "n_sessions": int(n_sessions),
                "workers": int(workers),
                "gateways": 2,
                "transport": "tcp",
                "failover_ms_median": round(float(np.median(fo_ms)), 1),
                "failover_ms_max": round(float(np.max(fo_ms)), 1),
                "reconnects": int(reconnects),
                "moved_receipts": int(moved),
                "resumed_sessions": int(resumed),
                "contract_ok": ok,
            }
        )
    return rows


if __name__ == "__main__":
    import json

    print(json.dumps(wire_failover_smoke()))
