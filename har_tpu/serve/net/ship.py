"""Journal shipping: the failover hand-off currency over the wire.

Until now "multi-host" failover had one shared-disk dependency left:
the controller restored a dead worker's partition by READING ITS
JOURNAL DIRECTORY off a filesystem both processes could see.  This
module removes it.  Each worker host runs a tiny SHIP AGENT (a
separate OS process — it survives the worker's SIGKILL the way a
host-level daemon survives a process crash) that serves that host's
journal directories as chunked reads, and the adopting side pulls the
dead worker's segments + newest snapshot over the PR-12 RPC transport
into a private staging directory, verifies them, and only then lets
the recovery layer replay a single record.

The protocol, and why each piece exists:

  framing     every chunk rides the journal's own CRC record framing
              (the wire frame IS ``journal.encode_record``), so a chunk
              corrupted in transit dies at the frame decoder before it
              can touch the staged copy;

  chunk acks  the transfer is a PULL: each ``ship_chunk`` RPC names an
              explicit ``(file, offset, n)`` and its response is the
              per-chunk acknowledgement.  Retries ride the RPC layer's
              backoff + request-id dedup, and a re-shipped chunk is
              idempotent BY OFFSET — asking twice writes once;

  resume      the receiver appends a ``ship_chunk`` record to a durable
              ship log (``ship.log``, same record framing) only AFTER
              the chunk's bytes are fsynced into the ``.part`` file.
              A crash on either end resumes from the last durable
              chunk: the log's replay gives the verified offsets and
              any unrecorded ``.part`` tail (a torn receive) is
              truncated away;

  digests     every file carries its whole-file sha256 in the manifest,
              checked BEFORE the ``.part`` is renamed into place.  A
              mismatch — torn ship, bit rot, a lying peer — is refused
              loudly and the file re-ships from offset 0; it is never
              replayed.  ``journal.load_journal`` enforces the same
              rule structurally: a directory holding ``ship.log``
              without ``ship.done`` cannot be restored at all.

Chaos points (declared in ``serve/chaos.py``, SHIP_KILL_POINTS):
``mid_ship_send`` fires in the AGENT (the sending host dies mid-ship;
the restarted agent serves the resume), ``mid_ship_recv`` in the
receiving controller between chunks, ``post_ship_pre_drain`` after the
verified ship lands but before the restored engine drains.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import re
import shutil
import sys
import time
from typing import Callable

from har_tpu.serve.journal import (
    SHIP_DONE,
    SHIP_LOG,
    _SEG_PREFIX,
    _SNAP_PREFIX,
    _list_indexed,
    encode_record,
    read_segment,
)
from har_tpu.serve.net.rpc import (
    RpcClient,
    RpcConnectionRefused,
    RpcDeadlineExceeded,
    RpcServer,
)
from har_tpu.serve.net.wire import FrameError
from har_tpu.utils.durable import atomic_write, fsync_dir

# pull granularity: small enough that smoke-scale journals still span
# many chunks (the resume/kill matrix needs mid-transfer boundaries to
# land in), large enough that a real multi-MB journal is not RPC-bound
DEFAULT_CHUNK_BYTES = 256 << 10

# cluster.controller.RETIRED_MARKER, spelled locally: the agent process
# must stay engine-free (no FleetServer import, no jax backend) — it
# only streams bytes
_RETIRED = "retired.json"

# manifest entries name files RELATIVE to the journal dir, at most one
# directory deep (``snap.3/state.json``), from a closed character set —
# anything else is a hostile or corrupt peer
_SAFE_SEGMENT = re.compile(r"^[A-Za-z0-9._-]+$")


class ShipError(RuntimeError):
    """Ship protocol violation or an unrecoverably corrupt transfer
    (digest still wrong after the re-ship budget)."""


class ShipUnavailable(ShipError):
    """The ship agent is unreachable (refused, reset, or past its
    deadline budget): the failover PARKS and retries at a later poll —
    survivors keep serving; nothing is lost, only delayed."""


class ShipFaults:
    """Deterministic receiving-side storage faults for the ship tests
    (counter-based like ``LinkFaults`` — a chaos run replays exactly):

      ``torn``    the ``at``-th chunk writes only half its bytes and
                  aborts the transfer (the crash-between-write-and-
                  record model) — resume must truncate the unrecorded
                  tail and re-request the same offset;
      ``garble``  the ``at``-th chunk has one byte flipped before the
                  write (silent corruption past the wire CRC) — the
                  whole-file digest must refuse the ship and re-ship.
    """

    def __init__(self, action: str, at: int = 1):
        if action not in ("torn", "garble"):
            raise ValueError(f"unknown ship fault action {action!r}")
        self.action = action
        self.at = int(at)
        self.chunks = 0

    def hit(self) -> str | None:
        self.chunks += 1
        return self.action if self.chunks == self.at else None


class ShipTorn(OSError):
    """Raised by the injected ``torn`` fault after its half-write: the
    stand-in for the receiving process dying mid-chunk."""


def _check_rel(rel: str) -> str:
    parts = rel.split("/")
    if (
        len(parts) > 2
        or any(p in (".", "..") for p in parts)
        or not all(_SAFE_SEGMENT.match(p) for p in parts)
    ):
        raise ShipError(f"unsafe ship path {rel!r}")
    return rel


def _durable_prefix_len(path: str) -> int:
    """Byte length of the decodable record prefix of a framed log —
    exactly what ``read_segment`` would consume; everything past it is
    a torn tail."""
    import zlib

    from har_tpu.serve.journal import _HDR

    with open(path, "rb") as f:
        data = f.read()
    pos, n = 0, len(data)
    while pos + _HDR.size <= n:
        meta_len, payload_len, crc = _HDR.unpack_from(data, pos)
        end = pos + _HDR.size + meta_len + payload_len
        if end > n:
            break
        body = data[pos + _HDR.size : end]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            break
        try:
            json.loads(body[:meta_len].decode())
        except ValueError:
            break
        pos = end
    return pos


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def journal_manifest(root: str) -> list[dict]:
    """The file set a restore needs, with sizes and whole-file sha256
    digests: the newest COMPLETE snapshot's files plus every segment at
    or after its rotation point — exactly what ``load_journal`` reads.
    Files are hashed AS THEY ARE: a SIGKILL's torn segment tail ships
    byte-exact and the replay discards it there, same as in place."""
    snaps = _list_indexed(root, _SNAP_PREFIX)
    if not snaps:
        raise ShipError(
            f"{root} holds no complete snapshot — not a recoverable "
            "journal directory"
        )
    snap_path, base = snaps[-1]
    rels = [
        f"{_SNAP_PREFIX}{base}/{name}"
        for name in sorted(os.listdir(snap_path))
    ]
    rels.extend(
        os.path.basename(path)
        for path, idx in _list_indexed(root, _SEG_PREFIX)
        if idx >= base
    )
    out = []
    for rel in rels:
        path = os.path.join(root, _check_rel(rel))
        out.append(
            {
                "f": rel,
                "size": int(os.path.getsize(path)),
                "sha256": _sha256(path),
            }
        )
    return out


# ------------------------------------------------------------ the agent


class ShipAgent:
    """One host's journal file server: a selectors RPC loop over the
    directories under ``root`` (one per worker hosted there).  It holds
    NO fleet state and opens the journals read-only — the one write it
    performs is ``ship_retire``, the adopting controller durably
    marking a consumed partition on its home host."""

    def __init__(
        self,
        root: str,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        chaos: Callable[[str], None] | None = None,
    ):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.chaos = chaos
        self.rpc = RpcServer(self._handlers(), host=host, port=port)
        self._shutdown = False

    def _chaos(self, point: str) -> None:
        if self.chaos is not None:
            self.chaos(point)

    def _dir(self, name) -> str:
        path = os.path.join(self.root, _check_rel(str(name)))
        if not os.path.isdir(path):
            raise ShipError(f"no journal directory {name!r} on this host")
        return path

    # ------------------------------------------------------- handlers

    def _handlers(self) -> dict:
        def ship_list(meta, payload):
            dirs = []
            for name in sorted(os.listdir(self.root)):
                path = os.path.join(self.root, name)
                if not os.path.isdir(path):
                    continue
                holds_journal = any(
                    n.startswith((_SEG_PREFIX, _SNAP_PREFIX))
                    for n in os.listdir(path)
                ) or os.path.exists(os.path.join(path, _RETIRED))
                if not holds_journal:
                    continue
                dirs.append(
                    {
                        "name": name,
                        "retired": os.path.exists(
                            os.path.join(path, _RETIRED)
                        ),
                    }
                )
            return {"dirs": dirs}, b""

        def ship_manifest(meta, payload):
            return {"files": journal_manifest(self._dir(meta["dir"]))}, b""

        def ship_chunk(meta, payload):
            self._chaos("mid_ship_send")
            d = self._dir(meta["dir"])
            rel = _check_rel(str(meta["f"]))
            path = os.path.join(d, rel)
            off = int(meta["off"])
            n = int(meta["n"])
            if off < 0 or n <= 0:
                raise ShipError(f"bad chunk request off={off} n={n}")
            with open(path, "rb") as fh:
                fh.seek(off)
                data = fh.read(n)
            size = os.path.getsize(path)
            return (
                {
                    "f": rel,
                    "off": off,
                    "n": len(data),
                    "eof": off + len(data) >= size,
                },
                data,
            )

        def ship_retire(meta, payload):
            d = self._dir(meta["dir"])
            atomic_write(os.path.join(d, _RETIRED), payload.decode())
            return {}, b""

        def shutdown(meta, payload):
            self._shutdown = True
            return {}, b""

        return {
            "ship_list": ship_list,
            "ship_manifest": ship_manifest,
            "ship_chunk": ship_chunk,
            "ship_retire": ship_retire,
            "shutdown": shutdown,
        }

    # ----------------------------------------------------------- loop

    def serve_forever(self, *, max_idle_s: float = 0.0) -> int:
        try:
            while not self._shutdown:
                self.rpc.step(0.05)
                if (
                    max_idle_s
                    and time.monotonic() - self.rpc.last_activity
                    > max_idle_s
                ):
                    return 2  # orphaned: nobody ships from a dead suite
            return 0
        finally:
            self.close()

    def close(self) -> None:
        self.rpc.close()


# ----------------------------------------------------------- the client


class ShipClient:
    """One pooled connection to one host's ship agent.  Transport
    errors collapse to ``ShipUnavailable`` — the caller's policy is
    always the same (park the failover, retry at a later poll), so the
    finer taxonomy stops here."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        deadline_s: float = 5.0,
        retries: int = 2,
        stats=None,
    ):
        self.host = host
        self.port = int(port)
        self._client = RpcClient(
            host, port, deadline_s=deadline_s, retries=retries,
            stats=stats,
        )

    def bind_stats(self, stats) -> None:
        """Point the transport counters at the owning controller's
        ``net_stats`` (rebinding on adoption/takeover, like
        ``NetWorker.bind_stats``)."""
        self._client.stats = stats

    def _call(self, method, meta=None, payload=b""):
        from har_tpu.serve.net.rpc import RpcRemoteError

        try:
            return self._client.call(method, meta, payload)
        except (
            RpcConnectionRefused,
            RpcDeadlineExceeded,
            FrameError,
        ) as exc:
            raise ShipUnavailable(
                f"ship agent {self.host}:{self.port}: {exc}"
            ) from exc
        except RpcRemoteError as exc:
            # an agent-side refusal (unsafe path, no complete snapshot,
            # a bad request) is a SOURCE problem, not a link problem:
            # surface it as ShipError so the controller can quarantine
            # the partition instead of crash-looping on it
            raise ShipError(
                f"ship agent {self.host}:{self.port} refused "
                f"{method}: {exc}"
            ) from exc

    def list(self) -> list[dict]:
        meta, _ = self._call("ship_list")
        return list(meta.get("dirs") or [])

    def retired(self, src: str) -> bool:
        for entry in self.list():
            if entry.get("name") == src:
                return bool(entry.get("retired"))
        return False

    def manifest(self, src: str) -> list[dict]:
        meta, _ = self._call("ship_manifest", {"dir": src})
        return list(meta["files"])

    def chunk(self, src: str, f: str, off: int, n: int):
        return self._call(
            "ship_chunk", {"dir": src, "f": f, "off": int(off), "n": int(n)}
        )

    def retire(self, src: str, entry: dict) -> None:
        self._call(
            "ship_retire",
            {"dir": src},
            json.dumps(entry).encode(),
        )

    def shutdown(self) -> None:
        try:
            self._call("shutdown")
        except ShipUnavailable:
            pass

    def close(self) -> None:
        self._client.close()


# ------------------------------------------------- the durable ship log


class _ShipJournal:
    """Append-only receive log in the staging directory, the journal's
    own record framing: each record is fsynced before ``append``
    returns, so a record's presence IS its durability.  The torn tail a
    mid-append crash leaves is discarded by ``read_segment`` at replay
    — and TRUNCATED here at open, before any new append: the reader
    stops at the first torn record, so appending after an interior
    tear would make every later record unreachable and silently turn
    "resume from the last durable chunk" into "re-pull from scratch"
    on the next crash (the same rescue FleetJournal.flush performs for
    its segments)."""

    def __init__(self, dest: str):
        self.path = os.path.join(dest, SHIP_LOG)
        first = not os.path.exists(self.path)
        self._fh = open(self.path, "ab")
        if first:
            fsync_dir(dest)
        else:
            durable = _durable_prefix_len(self.path)
            if self._fh.tell() > durable:
                self._fh.truncate(durable)

    def append(self, meta: dict) -> None:
        self._fh.write(encode_record(meta))
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass


class _ShipProgress:
    """What the ship log's replay proves durable so far."""

    __slots__ = ("src", "manifest", "offsets", "done_files", "done")

    def __init__(self):
        self.src = None
        self.manifest = None
        self.offsets: dict[str, int] = {}
        self.done_files: set[str] = set()
        self.done = False


def replay_ship_log(dest: str) -> _ShipProgress:
    """Rebuild transfer progress from the durable ship log (resume
    path).  Unknown record types are skipped — forward compat, same
    stance as the fleet replay loop."""
    prog = _ShipProgress()
    path = os.path.join(dest, SHIP_LOG)
    if not os.path.exists(path):
        return prog
    records, _torn = read_segment(path)
    for meta, _payload in records:
        t = meta.get("t")
        if t == "ship_begin":
            prog.src = meta.get("src")
            prog.manifest = meta.get("files")
        elif t == "ship_chunk":
            # the chunk's bytes were fsynced into the .part before this
            # record existed: the durable offset advances to its end
            prog.offsets[meta["f"]] = int(meta["off"]) + int(meta["n"])
        elif t == "ship_void":
            # a digest refusal voided the file: re-ship from zero
            prog.offsets[meta["f"]] = 0
        elif t == "ship_file":
            prog.done_files.add(meta["f"])
        elif t == "ship_remanifest":
            # the SOURCE was alive and changed shape under a tail
            # (har_tpu.serve.net.tail): a snapshot rotated the segment
            # set.  Adopt the new manifest and forget progress on the
            # files it dropped — offsets for surviving files stand,
            # which is what makes the tail resume without re-pulling a
            # durable byte.
            prog.manifest = meta.get("files")
            keep = {e["f"] for e in prog.manifest or []}
            prog.offsets = {
                f: o for f, o in prog.offsets.items() if f in keep
            }
            prog.done_files = {
                f for f in prog.done_files if f in keep
            }
        elif t == "ship_done":
            prog.done = True
    return prog


# ------------------------------------------------------- the transfer


def fetch_journal(
    client: ShipClient,
    src: str,
    dest: str,
    *,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    chaos: Callable[[str], None] | None = None,
    stats=None,
    faults: ShipFaults | None = None,
    reships: int = 2,
) -> dict:
    """Pull journal directory ``src`` from a host's ship agent into the
    private staging directory ``dest`` — resumable, chunk-acked,
    digest-verified (module docstring has the protocol argument).
    Returns ``{"bytes", "chunks", "resumes", "reshipped", "files"}``;
    ``stats`` (a FleetStats) additionally receives the shipped_bytes /
    ship_chunks / ship_resumes counters.  Raises ``ShipUnavailable``
    when the agent is unreachable (the caller parks and retries) and
    ``ShipError`` when the source is provably corrupt (digest still
    wrong after ``reships`` re-ships) — which is a refusal to restore,
    never a restore of bad bytes."""
    os.makedirs(dest, exist_ok=True)

    def _chaos(point: str) -> None:
        # the receiving side's kill-point site (mid_ship_recv): the
        # controller's chaos hook threads through here, so the harness
        # can die between chunks with durable progress on disk
        if chaos is not None:
            chaos(point)

    out = {"bytes": 0, "chunks": 0, "resumes": 0, "reshipped": 0,
           "files": 0}
    prog = replay_ship_log(dest)
    if prog.done:
        # every digest verified on a prior attempt; re-land the done
        # marker in case the crash fell between the ship_done record
        # and the marker write (otherwise the dir would stay refused
        # by the digest-before-replay guard forever)
        _write_done_marker(dest)
        return out
    manifest = client.manifest(src)
    if prog.manifest is not None and manifest != prog.manifest:
        # the SOURCE changed under the transfer (a dead worker's dir is
        # immutable, so this means the host was repaired/replaced — the
        # quarantine-lift path): the durable progress no longer
        # describes these bytes.  Void the whole staging dir and start
        # clean — resuming against a stale manifest would pull a
        # chimera of two sources that can never verify.
        shutil.rmtree(dest)
        os.makedirs(dest)
        prog = _ShipProgress()
    ship_journal = _ShipJournal(dest)
    try:
        if prog.manifest is None:
            ship_journal.append(
                {"t": "ship_begin", "src": src, "files": manifest}
            )
        else:
            # a prior attempt's durable progress: this fetch is a resume
            out["resumes"] = 1
            if stats is not None:
                stats.ship_resumes += 1
        for entry in manifest:
            name = _check_rel(entry["f"])
            if name in prog.done_files:
                continue
            final = os.path.join(dest, name)
            parent = os.path.dirname(final)
            if parent != dest:
                os.makedirs(parent, exist_ok=True)
            if (
                os.path.exists(final)
                and os.path.getsize(final) == int(entry["size"])
                and _sha256(final) == entry["sha256"]
            ):
                # crashed between the rename and its log record: the
                # verified file is already in place — re-log and move on
                ship_journal.append({"t": "ship_file", "f": name})
                continue
            _fetch_file(
                client, src, name, entry, dest, ship_journal,
                prog.offsets.get(name, 0), chunk_bytes, _chaos, stats,
                faults, reships, out,
            )
            out["files"] += 1
        ship_journal.append({"t": "ship_done"})
    finally:
        ship_journal.close()
    _write_done_marker(dest)
    return out


def _write_done_marker(dest: str) -> None:
    """The cheap done marker ``load_journal``'s digest-before-replay
    guard reads — written only once every file's digest verified."""
    with open(os.path.join(dest, SHIP_DONE), "wb") as fh:
        fh.flush()
        os.fsync(fh.fileno())
    fsync_dir(dest)


def _fetch_file(client, src, name, entry, dest, ship_journal, off,
                chunk_bytes, _chaos, stats, faults, reships, out):
    """One file's chunk loop + whole-file digest verdict, re-shipping
    from offset 0 on a refused digest up to ``reships`` times."""
    final = os.path.join(dest, name)
    size = int(entry["size"])
    attempts = 0
    while True:
        part = final + ".part"
        with open(part, "ab") as fh:
            if fh.tell() > off:
                # bytes past the last durable ship_chunk record: a torn
                # receive (crash between write and record) — discard,
                # exactly like the journal reader discards a torn tail
                fh.truncate(off)
            while off < size:
                _chaos("mid_ship_recv")
                meta, payload = client.chunk(src, name, off, chunk_bytes)
                if (
                    meta.get("f") != name
                    or int(meta.get("off", -1)) != off
                    or int(meta.get("n", -1)) != len(payload)
                ):
                    # a mis-sequenced response (reordered or duplicated
                    # frame surviving the rpc dedup) must never land at
                    # the wrong offset — refuse the response, keep the
                    # durable state, let the retry re-request.  This is
                    # a LINK-layer anomaly, not proof the source is
                    # corrupt, so it maps to the park-and-retry path
                    # (ShipUnavailable), never the quarantine
                    raise ShipUnavailable(
                        f"mis-sequenced ship chunk for {name!r}: asked "
                        f"off={off}, got {meta}"
                    )
                if not payload:
                    raise ShipError(
                        f"short read shipping {name!r} at off={off} — "
                        "the source file shrank under the manifest"
                    )
                action = faults.hit() if faults is not None else None
                if action == "garble":
                    payload = (
                        payload[:-1]
                        + bytes([payload[-1] ^ 0xFF])
                    )
                if action == "torn":
                    fh.write(payload[: max(1, len(payload) // 2)])
                    fh.flush()
                    os.fsync(fh.fileno())
                    raise ShipTorn(
                        f"injected torn receive at {name!r} off={off}"
                    )
                fh.write(payload)
                fh.flush()
                os.fsync(fh.fileno())
                ship_journal.append(
                    {"t": "ship_chunk", "f": name, "off": off,
                     "n": len(payload)}
                )
                off += len(payload)
                out["bytes"] += len(payload)
                out["chunks"] += 1
                if stats is not None:
                    stats.shipped_bytes += len(payload)
                    stats.ship_chunks += 1
        if _sha256(part) == entry["sha256"]:
            os.replace(part, final)
            fsync_dir(os.path.dirname(final))
            ship_journal.append({"t": "ship_file", "f": name})
            return
        # REFUSED: a torn or bit-rotted ship never reaches the replay.
        # Void the durable progress and re-ship the whole file.
        attempts += 1
        out["reshipped"] += 1
        try:
            os.remove(part)
        except OSError:
            pass
        ship_journal.append({"t": "ship_void", "f": name})
        off = 0
        if attempts > reships:
            raise ShipError(
                f"shipped copy of {name!r} failed its whole-file digest "
                f"{attempts} time(s) — the source is corrupt; refusing "
                "to restore from it"
            )


# --------------------------------------------------------- entry point


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="har serve-agent",
        description=(
            "journal ship agent: serves one host's worker journal "
            "directories (chunked, digest-manifested) to an adopting "
            "controller over the fleet RPC transport; prints one JSON "
            "ready line {host, port, pid, root} and serves until "
            "shutdown or idle timeout"
        ),
    )
    ap.add_argument("--root", required=True,
                    help="host directory containing worker journal dirs")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral; the ready line reports it")
    ap.add_argument("--max-idle-s", type=float, default=120.0,
                    help="exit when no RPC arrives for this long "
                         "(orphan protection); 0 disables")
    ap.add_argument("--chaos-point", default=None,
                    help="TESTING: os._exit(137) at the Nth hit of this "
                         "ship stage boundary (mid_ship_send) — a REAL "
                         "sender-host death mid-transfer")
    ap.add_argument("--chaos-at", type=int, default=1)
    ap.add_argument("--follow", action="append", default=[],
                    metavar="WID=HOST:PORT",
                    help="tail-follow a live worker's journal from its "
                         "ship agent and keep a warm replica (repeat "
                         "per source); turns this agent into a standby "
                         "whose staged copies are themselves shippable")
    ap.add_argument("--cycle-s", type=float, default=0.5,
                    help="standby tail cadence (with --follow)")
    return ap


def _parse_follow(specs):
    follows = {}
    for spec in specs:
        try:
            wid, addr = spec.split("=", 1)
            host, port = addr.rsplit(":", 1)
            follows[wid] = (host, int(port))
        except ValueError:
            raise SystemExit(
                f"--follow wants WID=HOST:PORT, got {spec!r}"
            )
    return follows


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.follow:
        # standby mode replays records through the fleet engine; the
        # import stays behind the flag so a plain agent remains
        # engine-free
        from har_tpu.serve.replica import StandbyHost

        host = StandbyHost(
            args.root, _parse_follow(args.follow), host=args.host,
            port=args.port, cycle_s=args.cycle_s,
        )
        print(
            json.dumps(
                {
                    "host": host.agent.rpc.host,
                    "port": host.agent.rpc.port,
                    "pid": os.getpid(),
                    "root": host.agent.root,
                    "follows": sorted(_parse_follow(args.follow)),
                }
            ),
            flush=True,
        )
        return host.serve_forever(max_idle_s=args.max_idle_s)
    chaos = None
    if args.chaos_point:
        from har_tpu.serve.net.worker import _HardKillPlan

        chaos = _HardKillPlan(args.chaos_point, args.chaos_at)
    agent = ShipAgent(args.root, host=args.host, port=args.port,
                      chaos=chaos)
    print(
        json.dumps(
            {
                "host": agent.rpc.host,
                "port": agent.rpc.port,
                "pid": os.getpid(),
                "root": agent.root,
            }
        ),
        flush=True,
    )
    return agent.serve_forever(max_idle_s=args.max_idle_s)


if __name__ == "__main__":  # pragma: no cover - subprocess entrypoint
    sys.exit(main())
