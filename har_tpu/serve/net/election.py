"""Replicated controller: wall-clock lease election over shared disk.

The in-process control plane has ONE controller; if it dies
mid-migration, ``FleetCluster.takeover`` can finish the job — but
something has to RUN takeover, and in PR 7 that something was the test
harness.  This module closes the loop: N ``ControllerReplica``
processes watch one lease file; exactly one holds the lease and drives
the cluster; when it stops renewing, a standby campaigns, fences the
old generation, and completes the takeover — the orphaned failover
finishes via the protocol alone.

The lease is a FILE on the cluster root (the same shared filesystem
the journals already require), written atomically
(``utils.durable.atomic_write``) and stamped with a WALL clock
(``time.time`` — monotonic clocks are not comparable across processes;
this is the transport layer's sanctioned wall-clock use, harlint
HL004's ``serve/net/`` allowlist):

    leader.json   {"leader": id, "gen": N, "expires": unix_seconds}
    election.lock O_CREAT|O_EXCL campaign mutex (stale-broken by age)

Election rules:

  1. the holder renews before ``expires``; a reader trusts an
     unexpired lease absolutely (standby);
  2. an expired (or absent) lease invites a campaign: take the lock,
     RE-READ the lease (the race loser sees the winner's fresh lease
     and stands down), write generation N+1 with your id, release;
  3. generations only grow — a deposed leader that wakes up sees a
     larger generation than its own and MUST resign (its renew is
     refused), so two processes never both believe they hold gen N+1;
  4. controller state is DERIVED, never trusted across generations:
     the winner rebuilds placement from actual worker ownership
     (``FleetCluster.takeover``), where a crashed hand-off's dual
     ownership resolves by the sessions' ``handoffs`` generation — the
     split-brain tie-break is per-session and journal-backed, not
     lease-math.

Clock skew bounds correctness the usual lease way: the lease must be
long relative to skew + write latency.  On loopback (this PR's
deployment) skew is zero; multi-host deployments tune ``lease_s`` up.
"""

from __future__ import annotations

import errno
import json
import os
import time

from har_tpu.serve.cluster.controller import ClusterConfig
from har_tpu.utils.durable import atomic_write

LEASE_FILE = "leader.json"
LOCK_FILE = "election.lock"
# a campaign lock older than this is a crashed campaigner, not a
# campaign in progress — broken by the next campaigner
STALE_LOCK_S = 10.0


class LeaderLease:
    """The lease file protocol: read / renew / campaign."""

    def __init__(self, root: str, *, lease_s: float = 1.0, wall=None):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.lease_s = float(lease_s)
        # injectable for tests; the default is the real wall clock —
        # cross-process comparability is the point
        self._wall = wall or time.time
        self._path = os.path.join(self.root, LEASE_FILE)
        self._lock = os.path.join(self.root, LOCK_FILE)

    def read(self) -> dict | None:
        try:
            with open(self._path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def holder(self) -> str | None:
        """The current leader id, None if the lease is expired/absent."""
        lease = self.read()
        if lease is None or self._wall() >= lease.get("expires", 0.0):
            return None
        return lease.get("leader")

    def renew(self, leader_id: str, generation: int) -> bool:
        """Extend the lease — refused (False) when the file no longer
        names this (leader, generation): a deposed leader MUST resign
        on a refused renew, never overwrite the successor."""
        lease = self.read()
        if lease is not None and (
            lease.get("gen", 0) > generation
            or (
                lease.get("gen", 0) == generation
                and lease.get("leader") != leader_id
            )
        ):
            return False
        atomic_write(
            self._path,
            json.dumps(
                {
                    "leader": leader_id,
                    "gen": int(generation),
                    "expires": self._wall() + self.lease_s,
                }
            ),
        )
        return True

    def release(self, leader_id: str, generation: int) -> bool:
        """Give the lease up EARLY — a draining holder expires its own
        lease (``expires = now``) instead of letting standbys wait out
        the full term, so a planned hand-off flips as fast as a crash
        detection, minus the detection.  Refused (False) under the same
        fencing as ``renew``: only the current (leader, generation) may
        release, a deposed holder's late release must not clip the
        successor's lease."""
        lease = self.read()
        if lease is not None and (
            lease.get("gen", 0) > generation
            or (
                lease.get("gen", 0) == generation
                and lease.get("leader") != leader_id
            )
        ):
            return False
        atomic_write(
            self._path,
            json.dumps(
                {
                    "leader": leader_id,
                    "gen": int(generation),
                    "expires": self._wall(),
                }
            ),
        )
        return True

    def campaign(self, leader_id: str) -> int | None:
        """Try to take an expired lease: lock, re-read, write gen+1.
        Returns the won generation, or None (lease alive, or another
        campaigner holds the lock)."""
        lease = self.read()
        if lease is not None and self._wall() < lease.get("expires", 0.0):
            return None  # alive: stand by
        if not self._acquire_lock():
            return None
        try:
            # re-read under the lock: the race loser sees the winner's
            # fresh lease and stands down
            lease = self.read()
            if lease is not None and self._wall() < lease.get(
                "expires", 0.0
            ):
                return None
            gen = int(lease.get("gen", 0)) + 1 if lease else 1
            atomic_write(
                self._path,
                json.dumps(
                    {
                        "leader": leader_id,
                        "gen": gen,
                        "expires": self._wall() + self.lease_s,
                    }
                ),
            )
            return gen
        finally:
            self._release_lock()

    def _acquire_lock(self) -> bool:
        try:
            fd = os.open(self._lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError as exc:
            if exc.errno != errno.EEXIST:
                return False
            # stale-lock breaking: a campaigner that died with the lock
            # must not wedge elections forever
            try:
                age = self._wall() - os.path.getmtime(self._lock)
            except OSError:
                return False
            if age < STALE_LOCK_S:
                return False
            try:
                os.unlink(self._lock)
                fd = os.open(
                    self._lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY
                )
            except OSError:
                return False
        # identity check closes the stale-breaking TOCTOU: a peer that
        # read the OLD lock's age may unlink the lock WE just created
        # and mint its own — if the path no longer names our inode,
        # we did not win (only the holder whose fd and path agree did)
        try:
            st_fd = os.fstat(fd)
        finally:
            os.close(fd)
        try:
            st_path = os.stat(self._lock)
        except OSError:
            return False
        return (st_path.st_ino, st_path.st_dev) == (
            st_fd.st_ino, st_fd.st_dev,
        )

    def _release_lock(self) -> None:
        try:
            os.unlink(self._lock)
        except OSError:
            pass


class ControllerReplica:
    """One controller replica: ``step()`` it periodically (its process
    main loop) and it renews or campaigns as the lease dictates.

    On winning a campaign the replica connects to the worker addresses
    (``(worker_id, host, port, journal_dir)`` tuples), takes over the
    responsive ones and completes any orphaned failover —
    ``NetCluster.takeover`` is the inherited, idempotent machinery.
    Events the takeover drains accumulate on ``self.events`` for the
    replica's consumer.
    """

    def __init__(
        self,
        replica_id: str,
        model,
        root: str,
        worker_addrs,
        *,
        config: ClusterConfig | None = None,
        loader=None,
        lease_s: float = 1.0,
        deadline_s: float = 2.0,
        wall=None,
    ):
        self.replica_id = str(replica_id)
        self.model = model
        self.root = os.path.abspath(os.path.expanduser(root))
        self.worker_addrs = list(worker_addrs)
        self.config = config
        self.loader = loader
        self.deadline_s = float(deadline_s)
        self.lease = LeaderLease(root, lease_s=lease_s, wall=wall)
        self.generation = 0
        self.cluster = None
        self.events: list = []
        self.takeovers = 0
        # True between winning a campaign and a COMPLETED takeover: a
        # takeover that raises (a slow worker timing out mid-attach)
        # must not strand the held lease — the holder renews and
        # retries instead of standing by against its own lease
        self._holds_mandate = False

    @property
    def is_leader(self) -> bool:
        return self.cluster is not None

    def step(self, *, poll: bool = True) -> str:
        """One duty cycle: leader -> renew (+ poll the cluster);
        mandate-holder whose takeover failed -> renew and retry it;
        standby -> campaign if the lease ran out.  Returns the role
        after the step ("leader" / "campaigning" / "standby")."""
        if self.cluster is not None:
            if not self.lease.renew(self.replica_id, self.generation):
                # deposed: a larger generation exists — resign, never
                # issue another RPC under a stale mandate
                self.resign()
                return "standby"
            if poll:
                self.events.extend(self.cluster.poll(force=True))
            return "leader"
        if self._holds_mandate:
            if not self.lease.renew(self.replica_id, self.generation):
                self._holds_mandate = False
                return "standby"
            return self._try_take_over()
        gen = self.lease.campaign(self.replica_id)
        if gen is None:
            return "standby"
        self.generation = gen
        self._holds_mandate = True
        return self._try_take_over()

    def _try_take_over(self) -> str:
        """Attempt the takeover under the held mandate; a transient
        failure (slow worker, I/O) keeps the mandate and retries next
        step — the lease stays renewed, so no leadership gap opens."""
        try:
            self._take_over()
        except Exception:
            return "campaigning"
        return "leader"

    def _take_over(self) -> None:
        from har_tpu.serve.net.client import NetWorker
        from har_tpu.serve.net.controller import NetCluster

        from har_tpu.serve.cluster.membership import (
            WorkerTimeout,
            WorkerUnavailable,
        )

        workers = []
        for wid, host, port, jdir in self.worker_addrs:
            w = NetWorker(
                wid, host, port, jdir, deadline_s=self.deadline_s
            )
            try:
                w.heartbeat()
            except WorkerTimeout:
                # SLOW, not dead — the no-strike rule applies to
                # takeover too: include the worker, never restore a
                # live worker's journal out from under it.  If it
                # stays unresponsive the takeover's own calls raise
                # and this replica simply retries next step().
                workers.append(w)
                continue
            except WorkerUnavailable:
                w.close()
                continue  # refused: dead — its journal dir is an
                #            orphan the takeover restores and migrates
            workers.append(w)
        self.cluster = NetCluster.takeover(
            self.model,
            self.root,
            workers,
            config=self.config,
            loader=self.loader,
        )
        self.takeovers += 1
        # the takeover's recovered-orphan drains deliver on the first
        # poll; collect them with this step
        self.events.extend(self.cluster.poll(force=True))

    def resign(self) -> None:
        """Stand down: drop the cluster attachment (sockets closed,
        worker processes untouched) and the mandate."""
        self._holds_mandate = False
        if self.cluster is not None:
            # fence only this controller's clients — never the workers
            for w in self.cluster._workers.values():
                w.close()
            self.cluster = None

    def close(self) -> None:
        self.resign()
