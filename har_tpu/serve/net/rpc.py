"""Request/response RPC over TCP for the fleet control plane.

Deliberately small: one frame out, one frame back, over a pooled
connection.  What it adds over a bare socket is exactly the failure
surface the cluster invariants must be re-proven against:

  - DEADLINES — every call carries a real wall deadline (the transport
    layer's one legitimate use of real clocks; harlint HL004's
    ``serve/net/`` allowlist).  A peer that answers late is
    ``RpcDeadlineExceeded``;
  - ERROR TAXONOMY — ``RpcConnectionRefused`` (nobody listening: the
    strongest cheap evidence a worker PROCESS is dead) is distinct
    from ``RpcDeadlineExceeded`` (a slow link or a busy worker — NOT
    death evidence; the membership prober must not spend a probe
    strike on it, see ``Membership.note_timeout``);
  - RETRIES — deadline-exceeded calls retry through the shared
    ``utils.backoff`` policy with the SAME request id, so a retry of a
    request the peer already executed is deduplicated server-side
    (exactly-once per request id), never re-executed;
  - DUPLICATE DELIVERY — the server answers every frame it receives;
    a duplicated request (retry or ``LinkFaults`` injection) is
    answered from a bounded response cache.  The client discards
    responses whose id is not the one in flight (a late answer to a
    timed-out earlier request must not be misread as the current one);
  - REMOTE ERRORS — a handler exception crosses back as
    ``RpcRemoteError`` carrying the exception class name, so the
    caller can re-raise domain errors (``AdmissionError``) that the
    control plane's hand-off fallback logic dispatches on.

``LinkFaults`` is the partition-tolerance matrix's deterministic link
impairment: delay (deadline blows, peer still executed), drop (frame
never sent) or duplicate (frame sent twice) the first N matching
calls — no RNG, so a chaos run replays exactly.
"""

from __future__ import annotations

import itertools
import os
import socket
import time

from har_tpu.serve.net.wire import (
    FrameBuffer,
    FrameError,
    encode_frame,
)
from har_tpu.utils.backoff import Backoff, BackoffPolicy


class RpcError(RuntimeError):
    """Transport-level RPC failure."""


class RpcConnectionRefused(RpcError):
    """Nobody is listening at the peer address (or the connection was
    reset mid-call): the worker PROCESS is gone — death evidence."""


class RpcDeadlineExceeded(RpcError):
    """The peer did not answer inside the deadline: slow link or busy
    worker — retry evidence, never death evidence on its own."""


class RpcRemoteError(RpcError):
    """The remote handler raised: ``kind`` is the exception class name,
    the message its text.  The call REACHED a live worker — remote
    errors renew the lease like any successful round trip."""

    def __init__(self, kind: str, message: str):
        super().__init__(f"{kind}: {message}")
        self.kind = kind


class LinkFaults:
    """Deterministic link impairment for the partition matrix.

    Applies ``action`` to the first ``times`` client calls whose method
    name starts with ``method`` (empty = all):

      ``delay``  the request is sent, then the client sleeps past its
                 own deadline before reading — the peer EXECUTED the
                 call but the answer is late (the retry-dedup case);
      ``drop``   the request frame is never sent — a blackholed link
                 (the dropped-probe case);
      ``dup``    the request frame is sent twice — duplicated delivery
                 (the server-side dedup case).

    Counter-based, not random: the matrix must replay exactly.
    """

    def __init__(self, action: str, method: str = "", times: int = 1):
        if action not in ("delay", "drop", "dup"):
            raise ValueError(f"unknown link-fault action {action!r}")
        self.action = action
        self.method = method
        self.times = int(times)
        self.fired = 0

    def hit(self, method: str) -> str | None:
        if self.fired >= self.times or not method.startswith(self.method):
            return None
        self.fired += 1
        return self.action


def _recv_into(
    sock: socket.socket, buf: FrameBuffer, deadline: float, stats=None
):
    """Feed one recv into ``buf`` honoring the absolute monotonic
    ``deadline``; raises socket.timeout past it, RpcConnectionRefused
    on a peer hangup."""
    remaining = deadline - time.monotonic()
    if remaining <= 0:
        raise socket.timeout("rpc deadline exceeded")
    sock.settimeout(remaining)
    chunk = sock.recv(1 << 16)
    if not chunk:
        raise RpcConnectionRefused("peer closed the connection")
    if stats is not None:
        stats.rpc_bytes_rx += len(chunk)
    buf.feed(chunk)


# process-unique client-id counter: ``id(self)`` is reusable after GC
# (a resurrected controller's fresh client could then be answered from
# a dead client's dedup cache entry) — a monotone counter never is
_CID_COUNTER = itertools.count()


class RpcClient:
    """One pooled connection to one worker address.

    ``stats`` (a ``FleetStats``) receives the transport counters —
    ``rpc_sent`` / ``rpc_retries`` / ``rpc_bytes_tx`` / ``rpc_bytes_rx``
    and the ``rpc_rtt`` histogram; ``faults`` injects link impairments
    (``LinkFaults``).  ``cid`` identifies this client in the server's
    duplicate-dedup cache and defaults to pid+object id — unique per
    live client object, which is all dedup needs.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        deadline_s: float = 2.0,
        retries: int = 2,
        connect_timeout_s: float = 1.0,
        stats=None,
        faults: LinkFaults | None = None,
        seed: int = 0,
    ):
        self.host = host
        self.port = int(port)
        self.deadline_s = float(deadline_s)
        self.retries = int(retries)
        self.connect_timeout_s = float(connect_timeout_s)
        self.stats = stats
        self.faults = faults
        self._sock: socket.socket | None = None
        self._buf = FrameBuffer()
        self._rid = 0
        self._cid = f"{os.getpid()}.{next(_CID_COUNTER)}"
        self._backoff = Backoff(
            BackoffPolicy(base_ms=20.0, cap_ms=500.0), seed=seed
        )

    # ----------------------------------------------------- connection

    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s
            )
        except socket.timeout:
            raise RpcDeadlineExceeded(
                f"connect to {self.host}:{self.port} timed out"
            )
        except OSError as exc:
            raise RpcConnectionRefused(
                f"connect to {self.host}:{self.port}: {exc}"
            )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._buf = FrameBuffer()
        return sock

    def _drop_connection(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._buf = FrameBuffer()

    def close(self) -> None:
        self._drop_connection()

    # ----------------------------------------------------------- call

    def call(
        self,
        method: str,
        meta: dict | None = None,
        payload: bytes = b"",
        *,
        deadline_s: float | None = None,
        retries: int | None = None,
    ) -> tuple[dict, bytes]:
        """One request/response round trip.  Deadline-exceeded attempts
        retry (same request id — the peer's dedup makes an executed-
        but-unanswered attempt exactly-once); connection-refused fails
        fast: that evidence belongs to the failure detector, not a
        retry loop."""
        deadline_s = self.deadline_s if deadline_s is None else deadline_s
        budget = self.retries if retries is None else int(retries)
        self._rid += 1
        rid = self._rid
        request = dict(meta or {})
        request["m"] = method
        request["id"] = rid
        request["cid"] = self._cid
        frame = encode_frame(request, payload)
        attempt = 0
        while True:
            try:
                out = self._attempt(method, rid, frame, deadline_s)
                # a success ends the retry episode: the next failure
                # starts at the base delay, not wherever an earlier
                # episode left the schedule (Backoff's own contract)
                self._backoff.reset()
                return out
            except RpcDeadlineExceeded:
                # the in-flight request is ambiguous (executed or not);
                # drop the connection so a late answer can never be
                # misread, and retry with the SAME id — dedup upgrades
                # "ambiguous" to "exactly once"
                self._drop_connection()
                attempt += 1
                if attempt > budget:
                    raise
                if self.stats is not None:
                    self.stats.rpc_retries += 1
                time.sleep(self._backoff.next_ms() / 1e3)
            except (RpcConnectionRefused, FrameError):
                self._drop_connection()
                raise
        # unreachable

    def _attempt(
        self, method: str, rid: int, frame: bytes, deadline_s: float
    ) -> tuple[dict, bytes]:
        action = self.faults.hit(method) if self.faults is not None else None
        sock = self._connect()
        t0 = time.monotonic()
        deadline = t0 + deadline_s
        try:
            if action != "drop":
                # counters inside the send branch: a dropped frame was
                # never on the wire, a duplicated one was on it twice —
                # the partition matrix reads these as measurements
                sock.sendall(frame)
                if self.stats is not None:
                    self.stats.rpc_sent += 1
                    self.stats.rpc_bytes_tx += len(frame)
                if action == "dup":
                    sock.sendall(frame)
                    if self.stats is not None:
                        self.stats.rpc_bytes_tx += len(frame)
            if action == "delay":
                # the request is on the wire (the peer will execute
                # it); the answer is past our deadline by construction
                time.sleep(deadline_s)
            while True:
                got = self._buf.next_frame()
                while got is None:
                    _recv_into(sock, self._buf, deadline, self.stats)
                    got = self._buf.next_frame()
                resp, rpayload = got
                if resp.get("id") == rid:
                    break
                # a late answer to an earlier timed-out request on a
                # reused connection: discard and keep reading
        except socket.timeout:
            raise RpcDeadlineExceeded(
                f"{method} to {self.host}:{self.port} exceeded "
                f"{deadline_s:.3f}s"
            )
        except (ConnectionError, BrokenPipeError, OSError) as exc:
            if isinstance(exc, RpcError):
                raise
            raise RpcConnectionRefused(
                f"{method} to {self.host}:{self.port}: {exc}"
            )
        if self.stats is not None:
            self.stats.rpc_rtt.record((time.monotonic() - t0) * 1e3)
        if "err" in resp:
            raise RpcRemoteError(resp["err"], resp.get("msg", ""))
        return resp, rpayload


class RpcServer:
    """Frame-at-a-time RPC server over a selectors loop.

    Single-threaded by design: handlers run strictly serialized, so the
    FleetServer behind them needs no locking — the same "one scheduler
    thread" stance the engine itself takes.  Multiple concurrent
    connections are fine (two controllers during a split brain); their
    frames interleave at frame granularity.

    ``handlers`` maps method name -> ``fn(meta, payload) -> (meta,
    payload)``.  Handler exceptions become error responses (class name
    + message), never a dead server.  Responses are cached per
    ``(cid, id)`` in a bounded table so duplicated frames (link retry,
    fault injection) are answered without re-executing the handler.

    ``admission`` is the gateway's edge-shed hook: called with
    ``(meta, payload_len)`` from the frame HEADER as soon as it has
    arrived — before the payload is assembled, CRC-checked or decoded.
    Returning a reason string refuses the frame: its payload bytes are
    discarded as they arrive (``FrameBuffer.skip_frame``) and the
    client is answered ``{"shed": reason}`` addressed to its request
    id.  Frames whose ``(cid, id)`` already sits in the dedup cache
    bypass admission and are re-answered from the cache — a RETRY of
    an executed request must never be re-judged into a shed (the
    client would re-deliver what the fleet already holds).
    """

    DEDUP_CAP = 512

    def __init__(
        self,
        handlers: dict,
        host: str = "127.0.0.1",
        port: int = 0,
        stats=None,
        admission=None,
    ):
        import selectors

        self.handlers = dict(handlers)
        self.admission = admission
        # worker-side transport counters (FleetStats): requests are
        # bytes_rx, responses are sent/bytes_tx — the mirror of the
        # controller-side client's view
        self.stats = stats
        self._sel = selectors.DefaultSelector()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
        )
        self._listener.bind((host, port))
        self._listener.listen(16)
        self._listener.setblocking(False)
        self._sel.register(self._listener, selectors.EVENT_READ, None)
        self.host, self.port = self._listener.getsockname()
        self._bufs: dict = {}
        # connections whose HEAD frame already passed admission but is
        # still assembling its payload (torn across recvs) — judged
        # once, not once per recv
        self._admitted: dict = {}
        # (cid, rid) -> encoded response frame, insertion-ordered so
        # eviction drops the oldest (dict preserves insertion order)
        self._dedup: dict = {}
        self.requests_served = 0
        self.last_activity = time.monotonic()
        self._closed = False

    # ------------------------------------------------------------ loop

    def step(self, timeout: float = 0.05) -> int:
        """Service ready sockets once; returns frames handled."""
        import selectors

        handled = 0
        for key, _ in self._sel.select(timeout):
            sock = key.fileobj
            if sock is self._listener:
                try:
                    conn, _ = self._listener.accept()
                except OSError:
                    continue
                conn.setblocking(False)
                conn.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                self._sel.register(conn, selectors.EVENT_READ, None)
                self._bufs[conn] = FrameBuffer()
                continue
            handled += self._service(sock)
        if handled:
            self.last_activity = time.monotonic()
        return handled

    def _service(self, sock) -> int:
        buf = self._bufs.get(sock)
        if buf is None:
            return 0
        try:
            chunk = sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return 0
        except OSError:
            self._hangup(sock)
            return 0
        if not chunk:
            self._hangup(sock)
            return 0
        if self.stats is not None:
            self.stats.rpc_bytes_rx += len(chunk)
        buf.feed(chunk)
        handled = 0
        try:
            while True:
                if self.admission is not None and not self._admitted.get(
                    sock
                ):
                    # the edge: judge the frame from its HEADER, before
                    # the payload exists as anything but socket bytes
                    head = buf.peek_header()
                    if head is None:
                        break
                    hmeta, plen = head
                    key = (hmeta.get("cid"), hmeta.get("id"))
                    cached = self._dedup.get(key)
                    if cached is not None and key[0] is not None:
                        # a retried frame the fleet already executed:
                        # answered from the cache, payload discarded —
                        # never re-judged into a shed
                        buf.skip_frame()
                        self._send(sock, cached)
                        handled += 1
                        continue
                    reason = self.admission(hmeta, plen)
                    if reason is not None:
                        buf.skip_frame()
                        if isinstance(reason, dict):
                            # a structured refusal (the gateway
                            # standby's {"moved": leader} receipt):
                            # sent verbatim, addressed to the request
                            resp = dict(reason)
                            resp["id"] = hmeta.get("id")
                        else:
                            resp = {"id": hmeta.get("id"), "shed": reason}
                        frame = encode_frame(resp)
                        self.requests_served += 1
                        if key[0] is not None and key[1] is not None:
                            self._dedup[key] = frame
                            while len(self._dedup) > self.DEDUP_CAP:
                                self._dedup.pop(next(iter(self._dedup)))
                        self._send(sock, frame)
                        handled += 1
                        continue
                    # admitted: remember it so a torn payload arriving
                    # over several recvs is never judged twice
                    self._admitted[sock] = True
                got = buf.next_frame()
                if got is None:
                    break
                self._admitted.pop(sock, None)
                self._dispatch(sock, *got)
                handled += 1
        except FrameError:
            # CRC mismatch / oversize / garbage: protocol violation —
            # this connection is dead; the peer reconnects clean
            self._hangup(sock)
        return handled

    def _hangup(self, sock) -> None:
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError):
            pass
        self._bufs.pop(sock, None)
        self._admitted.pop(sock, None)
        try:
            sock.close()
        except OSError:
            pass

    def _dispatch(self, sock, meta: dict, payload: bytes) -> None:
        rid = meta.get("id")
        key = (meta.get("cid"), rid)
        cached = self._dedup.get(key)
        if cached is not None:
            self._send(sock, cached)
            return
        method = meta.get("m", "")
        fn = self.handlers.get(method)
        if fn is None:
            frame = encode_frame(
                {"id": rid, "err": "UnknownMethod", "msg": method}
            )
        else:
            try:
                rmeta, rpayload = fn(meta, payload)
                resp = dict(rmeta or {})
                resp["id"] = rid
                frame = encode_frame(resp, rpayload)
            except SystemExit:
                raise
            except BaseException as exc:
                frame = encode_frame(
                    {
                        "id": rid,
                        "err": type(exc).__name__,
                        "msg": str(exc),
                    }
                )
        self.requests_served += 1
        if key[0] is not None and rid is not None:
            self._dedup[key] = frame
            while len(self._dedup) > self.DEDUP_CAP:
                self._dedup.pop(next(iter(self._dedup)))
        self._send(sock, frame)

    def _send(self, sock, frame: bytes) -> None:
        if self.stats is not None:
            self.stats.rpc_sent += 1
            self.stats.rpc_bytes_tx += len(frame)
        try:
            sock.setblocking(True)
            sock.sendall(frame)
            sock.setblocking(False)
        except OSError:
            self._hangup(sock)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for sock in list(self._bufs):
            self._hangup(sock)
        try:
            self._sel.unregister(self._listener)
        except (KeyError, ValueError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self._sel.close()
