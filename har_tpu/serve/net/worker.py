"""``har serve-worker`` — one FleetServer as an OS process on a socket.

The worker is the SAME crash-safe engine the in-process cluster runs
(an unmodified ``FleetServer`` + PR-4 journal); this module only puts a
real process boundary around it: a loopback TCP listener serving the
``ClusterWorker`` surface as RPCs, a REAL monotonic clock (no FakeClock
— deadlines and lease math run on actual time), and a real exit path
(``--chaos-point`` installs a kill plan that ``os._exit``s at the
chosen journal stage boundary — a genuine SIGKILL: the un-flushed
journal suffix is genuinely lost, not simulated lost).

Startup handshake: after binding, the worker prints ONE JSON line
``{"worker_id", "host", "port", "pid"}`` to stdout and flushes — the
launcher reads it to learn the ephemeral port.  ``--max-idle-s`` exits
the process when no RPC arrives for that long, so an orphaned worker
(its controller test died) cannot outlive the suite.

The model comes from a named POOL (``--model demo``), not a pickle over
the wire: ``swap_model`` RPCs carry only the version string and the
worker resolves it locally — the same stance the journal takes (models
are runtime resources, records carry versions).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from har_tpu.serve.net import wire
from har_tpu.serve.net.rpc import RpcServer

# the named model pools a worker can serve.  "demo" matches the chaos
# harness's swap schedule: version A is the analytic demo model,
# version B its tau=5.0 variant — the same pair every in-process
# matrix run scores with, so wire runs stay bit-comparable.
_MODEL_POOLS = ("demo",)


def model_pool(spec: str) -> dict:
    if spec not in _MODEL_POOLS:
        raise ValueError(
            f"unknown model pool {spec!r}; choose from {_MODEL_POOLS}"
        )
    from har_tpu.serve.loadgen import AnalyticDemoModel

    return {"A": AnalyticDemoModel(), "B": AnalyticDemoModel(tau=5.0)}


class _HardKillPlan:
    """Journal chaos hook for a subprocess worker: at the ``at``-th hit
    of ``point``, ``os._exit`` — the kernel reclaims the process with
    the journal buffer un-flushed, exactly what a SIGKILL leaves."""

    def __init__(self, point: str, at: int):
        self.point = point
        self.at = int(at)
        self.hits = 0

    def __call__(self, point: str) -> None:
        if point != self.point:
            return
        self.hits += 1
        if self.hits == self.at:
            os._exit(137)


class WorkerHost:
    """One FleetServer behind an RpcServer.

    The handler table DELEGATES to a local ``ClusterWorker`` wrapped
    around the engine — the same object the in-process control plane
    drives — so the wire worker cannot drift from the in-process
    contract: every handler is codec + one shim call, and the shim is
    the single place the surface's semantics (evict's flush ordering,
    the undrained definition, adopt idempotence) live.
    """

    def __init__(
        self,
        worker_id: str,
        server,
        *,
        journal_dir: str | None = None,
        models: dict | None = None,
        host="127.0.0.1",
        port=0,
    ):
        from har_tpu.serve.cluster.worker import ClusterWorker

        self.worker_id = worker_id
        self.server = server
        self.shim = ClusterWorker(
            worker_id, server, journal_dir or ""
        )
        # version -> model, what swap RPCs resolve against (models are
        # runtime resources; only version strings cross the wire)
        self._models = dict(models or {})
        self._shutdown = False
        self.rpc = RpcServer(
            self._handlers(), host=host, port=port, stats=server.stats
        )

    # ------------------------------------------------------- handlers

    def _handlers(self) -> dict:
        s = self.server
        shim = self.shim

        def ok(meta=None, payload=b""):
            return dict(meta or {}), payload

        def heartbeat(meta, payload):
            shim.heartbeat()
            return ok()

        def push(meta, payload):
            n = shim.push(meta["sid"], wire.decode_samples(meta, payload))
            return ok({"r": int(n)})

        def push_many(meta, payload):
            # one frame per delivery round: the chunk-batch codec's
            # sample arrays are zero-copy views over the received
            # payload; the engine stages them straight into its
            # reserved StagingArena slots in delivery order
            items = wire.decode_chunk_batch(meta, payload)
            n = shim.push_many(
                [sid for sid, _ in items], [c for _, c in items]
            )
            return ok({"r": int(n)})

        def poll(meta, payload):
            events = shim.poll(force=bool(meta.get("force")))
            return wire.encode_events(events)

        def add_session(meta, payload):
            from har_tpu.serve.journal import monitor_from_state

            shim.add_session(
                meta["sid"],
                monitor=monitor_from_state(meta.get("mon")),
            )
            return ok()

        def disconnect(meta, payload):
            events = shim.disconnect_sessions(meta["sids"])
            return wire.encode_events(events)

        def adopt(meta, payload):
            shim.adopt(wire.decode_export(meta, payload))
            return ok()

        def export(meta, payload):
            return wire.encode_export(shim.export_session(meta["sid"]))

        def evict(meta, payload):
            shim.evict_session(meta["sid"])
            return ok()

        def owns(meta, payload):
            return ok({"r": shim.owns(meta["sid"])})

        def watermark(meta, payload):
            return ok({"r": int(shim.watermark(meta["sid"]))})

        def swap(meta, payload):
            version = meta["ver"]
            if shim.model_version() != version:
                model = self._models.get(version)
                if model is None:
                    raise ValueError(
                        f"version {version!r} not in this worker's "
                        f"model pool {sorted(self._models)}"
                    )
                shim.swap_model(model, version=version)
            return ok({"r": shim.model_version()})

        def model_version(meta, payload):
            return ok({"r": shim.model_version()})

        def resize(meta, payload):
            # not part of the ClusterWorker surface (the elastic
            # controller drives resize through FleetServer directly)
            if s.config.target_batch != int(meta["tb"]):
                s.resize(target_batch=int(meta["tb"]))
            return ok({"r": int(s.config.target_batch)})

        def geometry(meta, payload):
            return ok(shim.geometry())

        def accounting(meta, payload):
            return ok({"r": shim.accounting()})

        def final_accounting(meta, payload):
            return ok(shim.final_accounting())

        def control_stats(meta, payload):
            return ok(shim.control_stats())

        def sessions(meta, payload):
            return ok({"r": list(shim.sessions())})

        def generation(meta, payload):
            return ok({"r": shim.generation(meta["sid"])})

        def undrained(meta, payload):
            return ok({"r": shim.undrained()})

        def drift_reports(meta, payload):
            return wire.encode_drift_reports(shim.drift_reports())

        def note_failover_absorbed(meta, payload):
            shim.note_failover_absorbed()
            return ok()

        def note_migration_ms(meta, payload):
            shim.note_migration_ms(float(meta["ms"]))
            return ok()

        def stats_snapshot(meta, payload):
            return ok({"r": s.stats_snapshot()})

        def shutdown(meta, payload):
            self._shutdown = True
            return ok()

        return {
            "heartbeat": heartbeat,
            "push": push,
            "push_many": push_many,
            "poll": poll,
            "add_session": add_session,
            "disconnect": disconnect,
            "adopt": adopt,
            "export": export,
            "evict": evict,
            "owns": owns,
            "watermark": watermark,
            "swap": swap,
            "model_version": model_version,
            "resize": resize,
            "geometry": geometry,
            "accounting": accounting,
            "final_accounting": final_accounting,
            "control_stats": control_stats,
            "sessions": sessions,
            "generation": generation,
            "undrained": undrained,
            "drift_reports": drift_reports,
            "note_failover_absorbed": note_failover_absorbed,
            "note_migration_ms": note_migration_ms,
            "stats_snapshot": stats_snapshot,
            "shutdown": shutdown,
        }

    # ----------------------------------------------------------- loop

    def serve_forever(self, *, max_idle_s: float = 0.0) -> int:
        try:
            while not self._shutdown:
                self.rpc.step(0.05)
                if (
                    max_idle_s
                    and time.monotonic() - self.rpc.last_activity
                    > max_idle_s
                ):
                    return 2  # orphaned: controller went away
            return 0
        finally:
            self.close()

    def close(self) -> None:
        self.rpc.close()
        if self.server.journal is not None:
            try:
                self.server.journal.close()
            except OSError:
                pass


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="har serve-worker",
        description=(
            "one FleetServer worker process on a loopback socket "
            "(har_tpu.serve.net) — launched by `har serve --workers N "
            "--net`, the chaos matrix and the release gate; prints one "
            "JSON ready line {worker_id, host, port, pid} and serves "
            "the cluster RPC surface until shutdown or idle timeout"
        ),
    )
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--journal", required=True,
                    help="this worker's journal directory (the failover "
                         "currency: the controller restores it on death)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral; the ready line reports it")
    ap.add_argument("--model", default="demo", choices=list(_MODEL_POOLS))
    ap.add_argument("--window", type=int, default=200)
    ap.add_argument("--hop", type=int, default=200)
    ap.add_argument("--channels", type=int, default=3)
    ap.add_argument("--smoothing", default="ema",
                    choices=["ema", "vote", "none"])
    ap.add_argument("--max-sessions", type=int, default=4096)
    ap.add_argument("--target-batch", type=int, default=32)
    ap.add_argument("--max-delay-ms", type=float, default=0.0)
    ap.add_argument("--retries", type=int, default=1)
    ap.add_argument("--flush-every", type=int, default=512)
    ap.add_argument("--snapshot-every", type=int, default=40)
    ap.add_argument("--resume", action="store_true",
                    help="restore the fleet from --journal instead of "
                         "attaching fresh (worker process restart)")
    ap.add_argument("--max-idle-s", type=float, default=120.0,
                    help="exit when no RPC arrives for this long "
                         "(orphan protection); 0 disables")
    ap.add_argument("--chaos-point", default=None,
                    help="TESTING: os._exit(137) at the Nth hit of this "
                         "journal stage boundary — a REAL process kill "
                         "at a chosen kill point")
    ap.add_argument("--chaos-at", type=int, default=1)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from har_tpu.serve.engine import FleetConfig, FleetServer
    from har_tpu.serve.journal import JournalConfig

    models = model_pool(args.model)
    journal_config = JournalConfig(
        flush_every=args.flush_every, snapshot_every=args.snapshot_every
    )
    if args.resume:
        server = FleetServer.restore(
            args.journal,
            lambda ver: models.get(ver, models["A"]),
            journal_config=journal_config,
        )
    else:
        server = FleetServer(
            models["A"],
            window=args.window,
            hop=args.hop,
            channels=args.channels,
            smoothing=args.smoothing,
            config=FleetConfig(
                max_sessions=args.max_sessions,
                target_batch=args.target_batch,
                max_delay_ms=args.max_delay_ms,
                retries=args.retries,
            ),
            model_version="A",
            journal=args.journal,
            journal_config=journal_config,
        )
    if args.chaos_point:
        server.journal.chaos = _HardKillPlan(
            args.chaos_point, args.chaos_at
        )
    host = WorkerHost(
        args.worker_id, server, journal_dir=args.journal,
        models=models, host=args.host, port=args.port,
    )
    print(
        json.dumps(
            {
                "worker_id": args.worker_id,
                "host": host.rpc.host,
                "port": host.rpc.port,
                "pid": os.getpid(),
            }
        ),
        flush=True,
    )
    return host.serve_forever(max_idle_s=args.max_idle_s)


if __name__ == "__main__":  # pragma: no cover - subprocess entrypoint
    sys.exit(main())
