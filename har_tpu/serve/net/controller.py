"""``NetCluster`` — the FleetCluster control plane over real sockets.

The controller logic is INHERITED, not rewritten: ``FleetCluster``
already speaks only the worker surface (PR 13 refactored every
``worker.server.<attr>`` poke into a worker method), so running over
the wire is "construct it with ``NetWorker``s".  What this subclass
adds is the transport's bookkeeping:

  - ``net_stats`` — one ``FleetStats`` receiving the controller-side
    transport counters (``rpc_sent`` / ``rpc_retries`` /
    ``rpc_bytes_tx/rx`` + the ``rpc_rtt`` histogram, and the
    journal-ship counters ``shipped_bytes`` / ``ship_chunks`` /
    ``ship_resumes``) from every worker's RPC client and the ship
    clients;
  - ``observe_drift`` over the wire: per-session ``DriftReport``s
    pulled from every live worker (the ``drift_reports`` RPC) into the
    one fleet-global RetrainTrigger;
  - worker-process lifecycle helpers (``shutdown_workers``).

FAILOVER is shared-nothing when ``agents`` is given: the dead worker's
journal ships over the PR-12 transport (``har_tpu.serve.net.ship``)
from its host's ship agent into this controller's private staging
directory (``<root>/_shipped/<wid>``), is digest-verified, and only
then restored — the controller never reads another host's filesystem.
Without agents the inherited shared-disk path still works (the
loopback single-box deployment, and the bench lane's baseline).
Either way the per-session hand-offs ride the ``adopt`` RPC.  Death
needs REFUSED connections — ``WorkerTimeout`` never strikes — so a
live-but-slow worker is never restored out from under itself (the
fencing argument; see docs/multihost.md).

``launch_workers`` spawns ``har serve-worker`` OS subprocesses on
loopback ephemeral ports and wraps them in ``NetWorker``s;
``launch_agents`` does the same for the per-host journal-ship agents.
The ready handshake is one JSON line on the child's stdout.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from har_tpu.serve.cluster.controller import (
    RETIRED_MARKER,
    ClusterError,
    FleetCluster,
    PartitionUnavailable,
)
from har_tpu.serve.cluster.membership import WorkerUnavailable
from har_tpu.serve.journal import SHIP_DONE, JournalError
from har_tpu.serve.net import ship as shiplib
from har_tpu.serve.net.client import NetWorker
from har_tpu.serve.net.ship import (
    DEFAULT_CHUNK_BYTES,
    ShipClient,
    ShipError,
    ShipUnavailable,
)
from har_tpu.serve.stats import FleetStats

# controller-private staging area for shipped partitions, under the
# CONTROLLER's root (controller replicas share it — the same disk the
# election lease file already lives on), never on a worker host
SHIPPED_DIR = "_shipped"
# controller-private home for warm-standby tails (one subdirectory per
# followed worker, har_tpu.serve.replica.StandbyAgent): same disk as
# the staging area, but these fill CONTINUOUSLY while the workers are
# alive — at failover the finalized tail is the restore source and the
# ship path above becomes the fallback
REPLICA_DIR = "_replica"


class NetCluster(FleetCluster):
    """FleetCluster over NetWorkers.  Construct with
    ``_workers=[NetWorker, ...]`` (``launch_workers`` builds them);
    the positional in-process construction path is refused."""

    def __init__(
        self,
        model,
        root,
        *args,
        agents: dict | None = None,
        ship_chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        **kwargs,
    ):
        if kwargs.get("_workers") is None:
            raise ClusterError(
                "NetCluster needs _workers=[NetWorker, ...] — spawn "
                "them with har_tpu.serve.net.launch_workers (or "
                "`har serve-worker`)"
            )
        # ship plumbing first: the base constructor may already adopt
        # workers, and every seam below reads these
        self.net_stats = FleetStats()
        self._agents: dict = dict(agents or {})
        self._ship_chunk_bytes = int(ship_chunk_bytes)
        # wall time inside fetch_journal + per-transfer evidence — the
        # bench lane's ship_ms observable
        self.ship_ms = 0.0
        self.ship_transfers: list[dict] = []
        # partitions whose ship FAILED for a source-side reason (digest
        # never verifies, agent refuses the dir): parked like an
        # unreachable agent but NOT retried every poll — re-shipping a
        # provably corrupt source is wasted work until something
        # changes; register_agent() is the operator's "the source is
        # fixed/replaced" signal that lifts the quarantine
        self._ship_quarantine: dict = {}
        for client in self._agents.values():
            client.bind_stats(self.net_stats)
        super().__init__(model, root, *args, **kwargs)
        for w in self._workers.values():
            w.bind_stats(self.net_stats)

    def _adopt_worker(self, worker) -> None:
        super()._adopt_worker(worker)
        # workers attached after construction (takeover, scale-up)
        # join the shared transport counters too
        stats = getattr(self, "net_stats", None)
        if stats is not None:
            worker.bind_stats(stats)

    # ----------------------------------- shared-nothing journal ship

    def register_agent(self, worker_id, client: ShipClient) -> None:
        """(Re)bind a worker host's ship agent — the harness calls this
        after restarting a crashed agent (a host daemon coming back);
        parked failovers retry against it at the next poll, and a
        source-side quarantine (a ship that kept failing its digests)
        is lifted: a re-registered agent means the source changed."""
        old = self._agents.get(worker_id)
        if old is not None and old is not client:
            old.close()
        client.bind_stats(self.net_stats)
        self._agents[worker_id] = client
        self._ship_quarantine.pop(worker_id, None)

    def _staged_dir(self, wid) -> str:
        return os.path.join(self.root, SHIPPED_DIR, str(wid))

    def _fetch_partition(self, worker) -> str | None:
        """The journal-shipping RPC replacing the shared-disk read: pull
        the dead worker's segments + newest snapshot from its host's
        ship agent into the controller-private staging directory,
        digest-verified and resumable (har_tpu.serve.net.ship).  An
        unreachable agent raises ``PartitionUnavailable`` — the base
        control plane parks the failover and retries each poll.
        Without a registered agent the inherited shared-disk path
        applies (single-box deployment; the bench baseline)."""
        agent = self._agents.get(worker.worker_id)
        if agent is None:
            return super()._fetch_partition(worker)
        wid = worker.worker_id
        dest = self._staged_dir(wid)
        if os.path.exists(os.path.join(dest, RETIRED_MARKER)):
            return None
        # warm path first: a standby that tailed this worker holds
        # (verified-on-finalize) local bytes — zero-transfer failover.
        # Consulted even for a quarantined partition: the quarantine
        # indicts the SOURCE's ship, not the standby's already-landed
        # digest-checked copy (a finalize failure falls through to the
        # quarantine refusal below).
        warm = self._standby_partition(wid)
        if warm is not None:
            return warm
        if wid in self._ship_quarantine:
            # a prior ship failed for a SOURCE reason (digest never
            # verifies, agent refuses the dir) — don't re-pull a
            # provably bad source every poll; register_agent lifts this
            raise PartitionUnavailable(
                f"partition {wid!r} quarantined: "
                f"{self._ship_quarantine[wid]}"
            )
        try:
            if agent.retired(wid):
                return None
            self._ship(agent, wid, dest)
        except ShipUnavailable as exc:
            raise PartitionUnavailable(str(exc)) from exc
        except ShipError as exc:
            # the source itself is bad (torn beyond its digests, a
            # lying peer): refuse LOUDLY, quarantine the partition, and
            # park the failover — one corrupt partition must degrade
            # one partition, never crash-loop the whole control plane
            self._ship_quarantine[wid] = str(exc)
            import warnings

            warnings.warn(
                f"journal ship for dead worker {wid!r} REFUSED: {exc} "
                "— partition parked (its sessions stay down); fix or "
                "replace the source and register_agent() to retry",
                RuntimeWarning,
                stacklevel=2,
            )
            raise PartitionUnavailable(str(exc)) from exc
        return dest

    def _ship(self, agent: ShipClient, wid, dest: str) -> dict:
        t0 = time.perf_counter()
        out = shiplib.fetch_journal(
            agent, str(wid), dest,
            chunk_bytes=self._ship_chunk_bytes,
            chaos=self._chaos,
            stats=self.net_stats,
        )
        self.ship_ms += (time.perf_counter() - t0) * 1e3
        self.ship_transfers.append({"wid": wid, **out})
        return out

    def _commit_retired(self, dead_wid, entry: dict) -> None:
        """Propagate the consumed partition's retired marker back to
        its home host (best-effort: the staged copy's local marker is
        the commit point for this controller lineage; the source-side
        marker is what a FRESH controller with only agent addresses
        learns from)."""
        agent = self._agents.get(dead_wid)
        if agent is None:
            return
        try:
            agent.retire(str(dead_wid), entry)
        except ShipError:
            # ShipError covers ShipUnavailable too: a wiped/replaced
            # host refusing the marker must not crash the poll that
            # just completed the failover — the local marker rules,
            # and a later retire (or orphan discovery) re-lands it
            pass

    # -------------------------------------------- drift over the wire

    def observe_drift(self, trigger) -> None:
        """Fleet-GLOBAL drift escalation over the wire: pull every live
        worker's per-session ``DriftReport``s (the ``drift_reports``
        RPC, float64-exact codec) into the ONE aggregator, so K
        sessions drifting on a common channel fire the retrain trigger
        no matter how the router spread them across worker processes.
        Episode identity (``(generation, onset)``) and the stale-report
        guard live in the aggregator, so re-pulling the same stored
        report — or re-delivering it after a retried RPC — is a no-op
        by construction.  A worker that cannot answer contributes no
        evidence this round and feeds the failure detector instead."""
        for wid in list(self._workers):
            w = self._workers[wid]
            if not w.alive:
                continue
            try:
                reports = w.drift_reports()
            except WorkerUnavailable as exc:
                self._note_worker_failure(wid, exc)
                continue
            self._membership.note_ok(wid)
            for sid, report in reports:
                trigger.observe(sid, report)

    # -------------------------------------- in-process-only surfaces

    def add_worker(self, worker_id=None, *, rebalance: bool = False):
        raise ClusterError(
            "NetCluster cannot build a worker in-process; spawn one "
            "with `har serve-worker` / launch_workers and attach it "
            "via attach_worker()"
        )

    @classmethod
    def resume(cls, *args, **kwargs):
        raise ClusterError(
            "whole-node resume restores in-process workers; over the "
            "wire, restart the worker processes (har serve-worker "
            "--resume) and NetCluster.takeover the survivors"
        )

    @classmethod
    def takeover(
        cls,
        model,
        root: str,
        workers: list,
        *,
        agents: dict | None = None,
        config=None,
        clock=None,
        loader=None,
        fault_hook_for=None,
        journal_config=None,
        ship_chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    ) -> "NetCluster":
        """Controller-only restart over the wire: adopt the surviving
        worker processes, read retired markers from BOTH marker homes
        (``<root>/<wid>`` for the shared-disk deployment,
        ``<root>/_shipped/<wid>`` for shipped partitions), and complete
        any orphaned failover — including one a dead controller left
        MID-SHIP (the staged directory resumes from its last durable
        chunk via the recorded agent)."""
        root = os.path.abspath(os.path.expanduser(root))
        ledger: list[dict] = []
        seen: set = set()
        for base in (
            root,
            os.path.join(root, SHIPPED_DIR),
            # a failover completed FROM a warm standby tail writes its
            # marker into the replica home — the third marker home
            os.path.join(root, REPLICA_DIR),
        ):
            if not os.path.isdir(base):
                continue
            for name in sorted(os.listdir(base)):
                marker = os.path.join(base, name, RETIRED_MARKER)
                if not os.path.isfile(marker):
                    continue
                with open(marker) as f:
                    entry = json.load(f)
                if entry.get("worker_id") in seen:
                    continue  # marked on both sides: one ledger entry
                seen.add(entry.get("worker_id"))
                ledger.append(entry)
        cluster = cls(
            model,
            root,
            hop=workers[0].geometry()["hop"] if workers else 20,
            config=config,
            clock=clock,
            loader=loader,
            fault_hook_for=fault_hook_for,
            journal_config=journal_config,
            _workers=workers,
            _ledger=ledger,
            agents=agents,
            ship_chunk_bytes=ship_chunk_bytes,
        )
        cluster._recover_orphans()
        return cluster

    def _recover_orphans(self) -> None:
        """Finish failovers a dead controller left half-done, the
        shared-nothing way: a STAGED directory under ``_shipped/`` that
        is not retired is a partition whose migration the crash
        interrupted — resume the ship if its digests never finished
        verifying (``fetch_journal`` picks up from the last durable
        chunk), then restore, drain and hand off exactly like a first
        failover.  Agent-listed journal dirs owned by no live worker
        and no ledger entry are failovers that never even started —
        pulled the same way.  Without agents the inherited shared-disk
        scan applies."""
        if not self._agents:
            super()._recover_orphans()
            return
        owned = set(self._workers)
        ship_root = os.path.join(self.root, SHIPPED_DIR)
        staged = (
            sorted(
                n
                for n in os.listdir(ship_root)
                if os.path.isdir(os.path.join(ship_root, n))
            )
            if os.path.isdir(ship_root)
            else []
        )
        candidates = list(staged)
        for wid in self._agents:
            if wid not in candidates:
                candidates.append(wid)
        retired_wids = {e.get("worker_id") for e in self._ledger}
        for wid in candidates:
            if wid in owned or wid in retired_wids:
                continue
            dest = self._staged_dir(wid)
            if os.path.exists(os.path.join(dest, RETIRED_MARKER)):
                continue
            agent = self._agents.get(wid)
            try:
                if agent is not None and agent.retired(wid):
                    continue
            except ShipError:
                pass  # judge from local state; the ship below retries
            if not os.path.exists(os.path.join(dest, SHIP_DONE)):
                if agent is None:
                    continue  # unfetchable now; a later takeover retries
                try:
                    self._ship(agent, wid, dest)
                except ShipUnavailable:
                    continue  # agent down: park for a later takeover
                except ShipError as exc:
                    # a corrupt source must not kill the takeover —
                    # quarantine this partition, adopt everything else
                    self._ship_quarantine[wid] = str(exc)
                    import warnings

                    warnings.warn(
                        f"orphaned partition {wid!r} ship REFUSED: "
                        f"{exc} — quarantined; fix the source and "
                        "register_agent() to retry",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                    continue
            try:
                from har_tpu.serve.engine import FleetServer

                restored = FleetServer.restore(
                    dest, self._loader, clock=self._clock
                )
            except JournalError:
                continue  # not (yet) a restorable copy
            self.failovers += 1
            self._pending_events.extend(restored.flush())
            self._complete_failover(wid, restored)

    def attach_worker(self, worker: NetWorker, *, rebalance: bool = False):
        """Scale up with an already-running worker process; with
        ``rebalance`` the sessions its ring arcs now own migrate over
        (the inherited drain → hand-off → resume rails)."""
        self._adopt_worker(worker)
        if rebalance:
            self.rebalance()
        return worker.worker_id

    # ------------------------------------------------------ reporting

    def transport_stats(self) -> dict:
        """Controller-side RPC counters: calls, retries, bytes, rtt,
        and the journal-ship evidence (bytes/chunks/resumes + wall
        time inside fetch_journal)."""
        s = self.net_stats
        return {
            "rpc_sent": s.rpc_sent,
            "rpc_retries": s.rpc_retries,
            "rpc_bytes_tx": s.rpc_bytes_tx,
            "rpc_bytes_rx": s.rpc_bytes_rx,
            "rpc_rtt_p50_ms": s.rpc_rtt.percentile(50),
            "rpc_rtt_p99_ms": s.rpc_rtt.percentile(99),
            "shipped_bytes": s.shipped_bytes,
            "ship_chunks": s.ship_chunks,
            "ship_resumes": s.ship_resumes,
            "ship_ms": round(self.ship_ms, 3),
            # warm-standby evidence: bytes moved ON the failover path
            # (0 for a caught-up tail) and how many fetches the warm
            # path answered instead of a ship
            "failover_path_bytes": self.failover_path_bytes,
            "standby_fetches": self.standby_fetches,
            "standbys": len(self._standbys),
        }

    # ------------------------------------------------------ lifecycle

    def close(self) -> None:
        super().close()
        for client in self._agents.values():
            client.close()

    def shutdown_workers(self, timeout_s: float = 5.0) -> None:
        """Ask every live worker process to exit cleanly and reap the
        subprocess handles this controller launched."""
        for w in self._workers.values():
            if w.alive:
                w.shutdown()
        deadline = time.monotonic() + timeout_s
        for w in self._workers.values():
            proc = w.process
            if proc is None:
                continue
            try:
                proc.wait(max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


def launch_workers(
    root: str,
    n: int,
    *,
    model: str = "demo",
    window: int = 200,
    hop: int = 200,
    channels: int = 3,
    smoothing: str = "ema",
    max_sessions: int = 4096,
    target_batch: int = 32,
    max_delay_ms: float = 0.0,
    retries: int = 1,
    flush_every: int = 512,
    snapshot_every: int = 40,
    deadline_s: float = 2.0,
    probe_deadline_s: float = 0.25,
    rpc_retries: int = 2,
    max_idle_s: float = 120.0,
    chaos_worker: str | None = None,
    chaos_point: str | None = None,
    chaos_at: int = 1,
    stats: FleetStats | None = None,
    ready_timeout_s: float = 30.0,
    journal_root: str | None = None,
) -> list[NetWorker]:
    """Spawn ``n`` ``har serve-worker`` subprocesses on loopback
    ephemeral ports and return their ``NetWorker`` proxies.

    Journal layout: by default each worker journals under ``root/wK``
    (the shared-disk deployment — the controller can restore the
    directory in place).  ``journal_root`` moves every worker's journal
    to ``<journal_root>/hK/wK`` instead: one PRIVATE per-worker "host"
    directory the controller never reads — the shared-nothing layout
    the journal-shipping failover requires, with ``<journal_root>/hK``
    the root a per-host ship agent (``launch_agents``) serves.

    ``chaos_worker`` names the one worker started with
    ``--chaos-point`` (the wire chaos matrix's victim).  Each child's
    stderr is captured to ``<journal_dir>/worker.stderr.log`` for
    post-mortems."""
    os.makedirs(root, exist_ok=True)
    workers: list[NetWorker] = []
    procs: list[tuple[str, str, subprocess.Popen]] = []
    try:
        for i in range(int(n)):
            wid = f"w{i}"
            if journal_root is None:
                jdir = os.path.join(root, wid)
            else:
                jdir = os.path.join(journal_root, f"h{i}", wid)
            os.makedirs(jdir, exist_ok=True)
            cmd = [
                sys.executable, "-m", "har_tpu.serve.net.worker",
                "--worker-id", wid,
                "--journal", jdir,
                "--model", model,
                "--window", str(window),
                "--hop", str(hop),
                "--channels", str(channels),
                "--smoothing", smoothing,
                "--max-sessions", str(max_sessions),
                "--target-batch", str(target_batch),
                "--max-delay-ms", str(max_delay_ms),
                "--retries", str(retries),
                "--flush-every", str(flush_every),
                "--snapshot-every", str(snapshot_every),
                "--max-idle-s", str(max_idle_s),
            ]
            if chaos_point is not None and wid == chaos_worker:
                cmd += [
                    "--chaos-point", chaos_point,
                    "--chaos-at", str(chaos_at),
                ]
            err = open(os.path.join(jdir, "worker.stderr.log"), "wb")
            try:
                proc = subprocess.Popen(
                    cmd,
                    stdout=subprocess.PIPE,
                    stderr=err,
                    text=True,
                )
            finally:
                err.close()
            procs.append((wid, jdir, proc))
        for wid, jdir, proc in procs:
            ready = _read_ready_line(proc, wid, jdir, ready_timeout_s)
            workers.append(
                NetWorker(
                    wid,
                    ready["host"],
                    ready["port"],
                    jdir,
                    deadline_s=deadline_s,
                    probe_deadline_s=probe_deadline_s,
                    retries=rpc_retries,
                    stats=stats,
                    process=proc,
                )
            )
        return workers
    except BaseException:
        for _, _, proc in procs:
            try:
                proc.kill()
            except OSError:
                pass
        raise


def _read_ready_line(
    proc, wid, jdir, timeout_s: float,
    log_name: str = "worker.stderr.log",
) -> dict:
    """One JSON handshake line from the child's stdout; a child that
    dies or stalls before it is a launch failure with its stderr tail
    attached — never a hang."""
    import selectors

    deadline = time.monotonic() + timeout_s
    line = ""
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    try:
        while time.monotonic() < deadline:
            if sel.select(0.1):
                line = proc.stdout.readline()
                break
            if proc.poll() is not None:
                break
    finally:
        sel.close()
    if not line:
        tail = ""
        try:
            with open(os.path.join(jdir, log_name), "rb") as f:
                tail = f.read()[-800:].decode(errors="replace")
        except OSError:
            pass
        raise ClusterError(
            f"worker {wid!r} never printed its ready line "
            f"(rc={proc.poll()}); stderr tail: {tail}"
        )
    try:
        return json.loads(line)
    except ValueError:
        raise ClusterError(
            f"worker {wid!r} printed a garbled ready line: {line!r}"
        )


class AgentHandle:
    """One launched journal-ship-agent subprocess and its address.
    ``client()`` mints a FRESH ``ShipClient`` — every controller
    mandate (first controller, each takeover) builds its own
    connections and binds them to its own ``net_stats``."""

    def __init__(self, worker_id, root, host, port, process, *,
                 deadline_s: float = 5.0, retries: int = 2):
        self.worker_id = worker_id
        self.root = root
        self.host = host
        self.port = int(port)
        self.process = process
        self.deadline_s = float(deadline_s)
        self.retries = int(retries)

    def client(self, stats=None) -> ShipClient:
        return ShipClient(
            self.host, self.port,
            deadline_s=self.deadline_s, retries=self.retries,
            stats=stats,
        )


def launch_agents(
    roots: dict,
    *,
    chaos_agent=None,
    chaos_point: str | None = None,
    chaos_at: int = 1,
    deadline_s: float = 5.0,
    retries: int = 2,
    max_idle_s: float = 120.0,
    ready_timeout_s: float = 30.0,
) -> dict:
    """Spawn one journal-ship agent per worker host (``roots`` maps
    ``worker_id -> host directory`` — the directory CONTAINING that
    worker's journal dir, i.e. the ``hK`` the private
    ``launch_workers(journal_root=...)`` layout creates) and return
    ``{worker_id: AgentHandle}``.  ``chaos_agent`` names the one agent
    started with ``--chaos-point`` (``mid_ship_send`` — a real sender-
    host death mid-transfer).  Stderr lands in
    ``<host_root>/agent.stderr.log``."""
    handles: dict = {}
    procs: list = []
    try:
        for wid, host_root in roots.items():
            os.makedirs(host_root, exist_ok=True)
            cmd = [
                sys.executable, "-m", "har_tpu.serve.net.ship",
                "--root", host_root,
                "--max-idle-s", str(max_idle_s),
            ]
            if chaos_point is not None and wid == chaos_agent:
                cmd += [
                    "--chaos-point", chaos_point,
                    "--chaos-at", str(chaos_at),
                ]
            err = open(
                os.path.join(host_root, "agent.stderr.log"), "wb"
            )
            try:
                proc = subprocess.Popen(
                    cmd,
                    stdout=subprocess.PIPE,
                    stderr=err,
                    text=True,
                )
            finally:
                err.close()
            procs.append((wid, host_root, proc))
        for wid, host_root, proc in procs:
            ready = _read_ready_line(
                proc, f"agent:{wid}", host_root, ready_timeout_s,
                log_name="agent.stderr.log",
            )
            handles[wid] = AgentHandle(
                wid, host_root, ready["host"], ready["port"], proc,
                deadline_s=deadline_s, retries=retries,
            )
        return handles
    except BaseException:
        for _, _, proc in procs:
            try:
                proc.kill()
            except OSError:
                pass
        raise
