"""``NetCluster`` — the FleetCluster control plane over real sockets.

The controller logic is INHERITED, not rewritten: ``FleetCluster``
already speaks only the worker surface (PR 13 refactored every
``worker.server.<attr>`` poke into a worker method), so running over
the wire is "construct it with ``NetWorker``s".  What this subclass
adds is the transport's bookkeeping:

  - ``net_stats`` — one ``FleetStats`` receiving the controller-side
    transport counters (``rpc_sent`` / ``rpc_retries`` /
    ``rpc_bytes_tx/rx`` + the ``rpc_rtt`` histogram) from every
    worker's RPC client;
  - honest refusals for the in-process-only surfaces
    (``observe_drift`` maps over live ``FleetServer`` objects;
    ``add_worker`` builds one — neither exists on this side of a
    socket yet);
  - worker-process lifecycle helpers (``shutdown_workers``).

Failover is the inherited path verbatim: the dead worker's journal
directory is restored LOCALLY (loopback deployment = shared
filesystem; the journal is the hand-off currency exactly as designed)
and the per-session hand-offs ride the ``adopt`` RPC.  Death needs
REFUSED connections — ``WorkerTimeout`` never strikes — so a live-but-
slow worker is never restored out from under itself (the fencing
argument; see docs/multihost.md).

``launch_workers`` spawns ``har serve-worker`` OS subprocesses on
loopback ephemeral ports and wraps them in ``NetWorker``s; the ready
handshake is one JSON line on the child's stdout.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from har_tpu.serve.cluster.controller import ClusterError, FleetCluster
from har_tpu.serve.net.client import NetWorker
from har_tpu.serve.stats import FleetStats


class NetCluster(FleetCluster):
    """FleetCluster over NetWorkers.  Construct with
    ``_workers=[NetWorker, ...]`` (``launch_workers`` builds them);
    the positional in-process construction path is refused."""

    def __init__(self, model, root, *args, **kwargs):
        if kwargs.get("_workers") is None:
            raise ClusterError(
                "NetCluster needs _workers=[NetWorker, ...] — spawn "
                "them with har_tpu.serve.net.launch_workers (or "
                "`har serve-worker`)"
            )
        super().__init__(model, root, *args, **kwargs)
        self.net_stats = FleetStats()
        for w in self._workers.values():
            w.bind_stats(self.net_stats)

    def _adopt_worker(self, worker) -> None:
        super()._adopt_worker(worker)
        # workers attached after construction (takeover, scale-up)
        # join the shared transport counters too
        stats = getattr(self, "net_stats", None)
        if stats is not None:
            worker.bind_stats(stats)

    # -------------------------------------- in-process-only surfaces

    def observe_drift(self, trigger) -> None:
        raise ClusterError(
            "observe_drift maps over in-process FleetServers; the "
            "wire transport does not carry drift reports yet — run "
            "the adaptation loop per worker or in-process"
        )

    def add_worker(self, worker_id=None, *, rebalance: bool = False):
        raise ClusterError(
            "NetCluster cannot build a worker in-process; spawn one "
            "with `har serve-worker` / launch_workers and attach it "
            "via attach_worker()"
        )

    @classmethod
    def resume(cls, *args, **kwargs):
        raise ClusterError(
            "whole-node resume restores in-process workers; over the "
            "wire, restart the worker processes (har serve-worker "
            "--resume) and NetCluster.takeover the survivors"
        )

    def attach_worker(self, worker: NetWorker, *, rebalance: bool = False):
        """Scale up with an already-running worker process; with
        ``rebalance`` the sessions its ring arcs now own migrate over
        (the inherited drain → hand-off → resume rails)."""
        self._adopt_worker(worker)
        if rebalance:
            self.rebalance()
        return worker.worker_id

    # ------------------------------------------------------ reporting

    def transport_stats(self) -> dict:
        """Controller-side RPC counters: calls, retries, bytes, rtt."""
        s = self.net_stats
        return {
            "rpc_sent": s.rpc_sent,
            "rpc_retries": s.rpc_retries,
            "rpc_bytes_tx": s.rpc_bytes_tx,
            "rpc_bytes_rx": s.rpc_bytes_rx,
            "rpc_rtt_p50_ms": s.rpc_rtt.percentile(50),
            "rpc_rtt_p99_ms": s.rpc_rtt.percentile(99),
        }

    # ------------------------------------------------------ lifecycle

    def shutdown_workers(self, timeout_s: float = 5.0) -> None:
        """Ask every live worker process to exit cleanly and reap the
        subprocess handles this controller launched."""
        for w in self._workers.values():
            if w.alive:
                w.shutdown()
        deadline = time.monotonic() + timeout_s
        for w in self._workers.values():
            proc = w.process
            if proc is None:
                continue
            try:
                proc.wait(max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


def launch_workers(
    root: str,
    n: int,
    *,
    model: str = "demo",
    window: int = 200,
    hop: int = 200,
    channels: int = 3,
    smoothing: str = "ema",
    max_sessions: int = 4096,
    target_batch: int = 32,
    max_delay_ms: float = 0.0,
    retries: int = 1,
    flush_every: int = 512,
    snapshot_every: int = 40,
    deadline_s: float = 2.0,
    probe_deadline_s: float = 0.25,
    rpc_retries: int = 2,
    max_idle_s: float = 120.0,
    chaos_worker: str | None = None,
    chaos_point: str | None = None,
    chaos_at: int = 1,
    stats: FleetStats | None = None,
    ready_timeout_s: float = 30.0,
) -> list[NetWorker]:
    """Spawn ``n`` ``har serve-worker`` subprocesses under ``root`` (one
    journal directory each, ``root/wK``) on loopback ephemeral ports
    and return their ``NetWorker`` proxies.  ``chaos_worker`` names the
    one worker started with ``--chaos-point`` (the wire chaos matrix's
    victim).  Each child's stderr is captured to
    ``<journal_dir>/worker.stderr.log`` for post-mortems."""
    os.makedirs(root, exist_ok=True)
    workers: list[NetWorker] = []
    procs: list[tuple[str, str, subprocess.Popen]] = []
    try:
        for i in range(int(n)):
            wid = f"w{i}"
            jdir = os.path.join(root, wid)
            os.makedirs(jdir, exist_ok=True)
            cmd = [
                sys.executable, "-m", "har_tpu.serve.net.worker",
                "--worker-id", wid,
                "--journal", jdir,
                "--model", model,
                "--window", str(window),
                "--hop", str(hop),
                "--channels", str(channels),
                "--smoothing", smoothing,
                "--max-sessions", str(max_sessions),
                "--target-batch", str(target_batch),
                "--max-delay-ms", str(max_delay_ms),
                "--retries", str(retries),
                "--flush-every", str(flush_every),
                "--snapshot-every", str(snapshot_every),
                "--max-idle-s", str(max_idle_s),
            ]
            if chaos_point is not None and wid == chaos_worker:
                cmd += [
                    "--chaos-point", chaos_point,
                    "--chaos-at", str(chaos_at),
                ]
            err = open(os.path.join(jdir, "worker.stderr.log"), "wb")
            try:
                proc = subprocess.Popen(
                    cmd,
                    stdout=subprocess.PIPE,
                    stderr=err,
                    text=True,
                )
            finally:
                err.close()
            procs.append((wid, jdir, proc))
        for wid, jdir, proc in procs:
            ready = _read_ready_line(proc, wid, jdir, ready_timeout_s)
            workers.append(
                NetWorker(
                    wid,
                    ready["host"],
                    ready["port"],
                    jdir,
                    deadline_s=deadline_s,
                    probe_deadline_s=probe_deadline_s,
                    retries=rpc_retries,
                    stats=stats,
                    process=proc,
                )
            )
        return workers
    except BaseException:
        for _, _, proc in procs:
            try:
                proc.kill()
            except OSError:
                pass
        raise


def _read_ready_line(proc, wid, jdir, timeout_s: float) -> dict:
    """One JSON handshake line from the child's stdout; a child that
    dies or stalls before it is a launch failure with its stderr tail
    attached — never a hang."""
    import selectors

    deadline = time.monotonic() + timeout_s
    line = ""
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    try:
        while time.monotonic() < deadline:
            if sel.select(0.1):
                line = proc.stdout.readline()
                break
            if proc.poll() is not None:
                break
    finally:
        sel.close()
    if not line:
        tail = ""
        try:
            with open(
                os.path.join(jdir, "worker.stderr.log"), "rb"
            ) as f:
                tail = f.read()[-800:].decode(errors="replace")
        except OSError:
            pass
        raise ClusterError(
            f"worker {wid!r} never printed its ready line "
            f"(rc={proc.poll()}); stderr tail: {tail}"
        )
    try:
        return json.loads(line)
    except ValueError:
        raise ClusterError(
            f"worker {wid!r} printed a garbled ready line: {line!r}"
        )
