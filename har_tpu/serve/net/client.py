"""``NetWorker`` — the transport-backed twin of ``ClusterWorker``.

Same surface, every call an RPC: the controller (``FleetCluster`` /
``NetCluster``) cannot tell the two apart, which is the whole design —
every cluster invariant proven against the in-process shim re-proves
over the wire by swapping this class in behind the same seam.

Error mapping is the failure detector's food:

  - ``RpcConnectionRefused``  -> ``WorkerUnavailable``  (death evidence)
  - ``RpcDeadlineExceeded``   -> ``WorkerTimeout``      (slow link:
    probe re-paced, NO strike — see ``Membership.note_timeout``)
  - ``RpcRemoteError``        -> the remote exception re-raised by
    class name where the control plane dispatches on it
    (``AdmissionError`` drives the hand-off's next-candidate fallback)

``kill()`` here is a FENCE, not a kill: the controller-side refusal to
talk to a worker it has declared dead (the in-process stand-in fenced
the same way).  Killing the actual process is the harness's job — or
reality's.
"""

from __future__ import annotations

import os
import time
from typing import Hashable

from har_tpu.serve.cluster.membership import WorkerTimeout, WorkerUnavailable
from har_tpu.serve.engine import AdmissionError
from har_tpu.serve.net import wire
from har_tpu.serve.net.gateway import GatewayClient
from har_tpu.utils.backoff import Backoff, BackoffPolicy
from har_tpu.serve.net.rpc import (
    RpcClient,
    RpcConnectionRefused,
    RpcDeadlineExceeded,
    RpcRemoteError,
)

# remote exception class names re-raised as their local types: the
# hand-off fallback logic dispatches on AdmissionError (capacity
# refusal != failure-detector evidence)
_REMOTE_TYPES = {"AdmissionError": AdmissionError}


class NetWorker:
    """One remote FleetServer worker, addressed over loopback TCP.

    ``journal_dir`` must be the worker's journal directory on a
    filesystem the controller can read — failover restores the dead
    partition from DISK (the journal is the hand-off currency, exactly
    like the in-process design).  ``probe_deadline_s`` bounds the cheap
    heartbeat probe tighter than data-plane calls.
    """

    def __init__(
        self,
        worker_id,
        host: str,
        port: int,
        journal_dir: str,
        *,
        deadline_s: float = 2.0,
        probe_deadline_s: float = 0.25,
        retries: int = 2,
        stats=None,
        faults=None,
        process=None,
        seed: int = 0,
    ):
        self.worker_id = worker_id
        self.host = host
        self.port = int(port)
        # abspath-normalized: the controller's orphan scan compares
        # journal_dir strings against its own abspath'd root
        self.journal_dir = os.path.abspath(journal_dir)
        self.alive = True
        self.probe_deadline_s = float(probe_deadline_s)
        # the subprocess handle when this controller launched the
        # worker (launch_workers) — lifecycle convenience, never
        # consulted for liveness: the PROTOCOL decides liveness
        self.process = process
        self._client = RpcClient(
            host,
            port,
            deadline_s=deadline_s,
            retries=retries,
            stats=stats,
            faults=faults,
            seed=seed,
        )

    def bind_stats(self, stats) -> None:
        """Point the transport counters at the OWNING cluster's
        ``net_stats`` — rebinding on adoption, so a takeover
        controller's counters describe its own mandate."""
        self._client.stats = stats

    # ------------------------------------------------------------ call

    def _call(self, method, meta=None, payload=b"", **kw):
        if not self.alive:
            raise WorkerUnavailable(
                f"worker {self.worker_id!r} is fenced"
            )
        try:
            return self._client.call(method, meta, payload, **kw)
        except RpcDeadlineExceeded as exc:
            raise WorkerTimeout(
                f"worker {self.worker_id!r}: {exc}"
            ) from exc
        except RpcConnectionRefused as exc:
            raise WorkerUnavailable(
                f"worker {self.worker_id!r}: {exc}"
            ) from exc
        except RpcRemoteError as exc:
            local = _REMOTE_TYPES.get(exc.kind)
            if local is not None:
                raise local(str(exc)) from exc
            raise

    # ----------------------------------------------------- the RPCs

    def heartbeat(self) -> bool:
        self._call(
            "heartbeat", deadline_s=self.probe_deadline_s, retries=0
        )
        return True

    def push(self, session_id: Hashable, samples) -> int:
        meta, payload = wire.encode_samples(samples)
        meta["sid"] = session_id
        resp, _ = self._call("push", meta, payload)
        return int(resp["r"])

    def push_many(self, session_ids, chunks) -> int:
        """One batched push frame for a whole delivery round
        (``FleetServer.push_many``'s signature) — the pairs ride the
        chunk-batch codec in delivery order, one RPC instead of one
        per session.  The per-session ``push`` above stays
        (single-session compat, test-pinned equivalent)."""
        meta, payload = wire.encode_chunk_batch(
            zip(session_ids, chunks)
        )
        resp, _ = self._call("push_many", meta, payload)
        return int(resp["r"])

    def poll(self, *, force: bool = False) -> list:
        resp, payload = self._call("poll", {"force": bool(force)})
        return wire.decode_events(resp, payload)

    def add_session(self, session_id: Hashable, *, monitor=None) -> None:
        from har_tpu.serve.journal import monitor_state

        self._call(
            "add_session",
            {"sid": session_id, "mon": monitor_state(monitor)},
        )

    def disconnect_session(self, session_id: Hashable) -> list:
        return self.disconnect_sessions((session_id,))

    def disconnect_sessions(self, session_ids) -> list:
        resp, payload = self._call(
            "disconnect", {"sids": list(session_ids)}
        )
        return wire.decode_events(resp, payload)

    def adopt(self, export: dict) -> None:
        meta, payload = wire.encode_export(export)
        self._call("adopt", meta, payload)

    def owns(self, session_id: Hashable) -> bool:
        if not self.alive:
            return False
        try:
            resp, _ = self._call("owns", {"sid": session_id})
        except WorkerTimeout:
            # UNKNOWN is not "no": the hand-off's ownership pre-scan
            # exists to find a prior crashed attempt's durable adopt —
            # answering False for a merely-slow worker could mint a
            # second live copy.  Propagate; the caller retries later.
            raise
        except WorkerUnavailable:
            return False
        return bool(resp["r"])

    def watermark(self, session_id: Hashable) -> int:
        resp, _ = self._call("watermark", {"sid": session_id})
        return int(resp["r"])

    # ------------------------------------------- control-plane surface

    def export_session(self, session_id: Hashable) -> dict:
        resp, payload = self._call("export", {"sid": session_id})
        return wire.decode_export(resp, payload)

    def evict_session(self, session_id: Hashable) -> None:
        self._call("evict", {"sid": session_id})

    def sessions(self) -> tuple:
        resp, _ = self._call("sessions")
        return tuple(resp["r"])

    def session_count(self) -> int:
        resp, _ = self._call("control_stats")
        return int(resp["sessions"])

    def generation(self, session_id: Hashable) -> int:
        resp, _ = self._call("generation", {"sid": session_id})
        return int(resp["r"])

    def undrained(self) -> list:
        resp, _ = self._call("undrained")
        return list(resp["r"])

    def model_version(self) -> str:
        resp, _ = self._call("model_version")
        return str(resp["r"])

    def swap_model(self, model, *, version: str) -> None:
        """Broadcast half of the hot swap: only the VERSION crosses the
        wire — the worker resolves it from its local model pool (models
        are runtime resources, same stance as the journal's swap
        record).  The ``model`` argument keeps the ClusterWorker
        signature; a transport cannot ship a live model object."""
        self._call("swap", {"ver": version})

    def resize(self, target_batch: int) -> int:
        resp, _ = self._call("resize", {"tb": int(target_batch)})
        return int(resp["r"])

    def geometry(self) -> dict:
        resp, _ = self._call("geometry")
        return {k: v for k, v in resp.items() if k != "id"}

    def accounting(self) -> dict:
        resp, _ = self._call("accounting")
        return resp["r"]

    def final_accounting(self) -> dict:
        resp, _ = self._call("final_accounting")
        return {
            "accounting": resp["accounting"],
            "scored_by_version": resp["scored_by_version"],
        }

    def control_stats(self) -> dict:
        resp, _ = self._call("control_stats")
        return {k: v for k, v in resp.items() if k != "id"}

    def drift_reports(self) -> list:
        """Every monitored session's latest DriftReport, float64-exact
        across the wire (``wire.encode_drift_reports``): the aggregator
        sees the same z / log-ratio numbers and the same
        ``(generation, onset)`` episode ids it would in-process, so
        threshold verdicts and episode dedup cannot drift with the
        transport."""
        resp, payload = self._call("drift_reports")
        return wire.decode_drift_reports(resp, payload)

    def note_failover_absorbed(self) -> None:
        self._call("note_failover_absorbed")

    def note_migration_ms(self, ms: float) -> None:
        self._call("note_migration_ms", {"ms": float(ms)})

    def stats_snapshot(self) -> dict:
        resp, _ = self._call("stats_snapshot")
        return resp["r"]

    # ----------------------------------------------------- lifecycle

    def kill(self) -> None:
        """Fence: refuse all further calls from THIS controller.  The
        remote process (if still running) is untouched — fencing is a
        controller-side decision, the worker's own death is the
        process's (or the harness's) business."""
        self.alive = False
        self._client.close()

    def shutdown(self) -> None:
        """Ask the worker process to exit cleanly (journal closed)."""
        try:
            self._call("shutdown")
        except WorkerUnavailable:
            pass

    def close(self) -> None:
        self.alive = False
        self._client.close()


class HAGatewayClient(GatewayClient):
    """Front-door client for an ELECTED gateway pair — the lossless
    reconnect half of edge HA.

    Wraps every RPC (``_call``) in a redial-and-resume loop:

      - a dead connection (``RpcConnectionRefused`` — the gateway
        process is gone — or a deadline past the base client's own
        retry budget) re-resolves the leader by rotating through the
        configured addresses UNDER the shared ``utils/backoff.Backoff``
        policy (capped exponential, seeded jitter): the whole client
        population re-dials at a decaying, de-synchronized rate instead
        of stampeding the survivor at the lease flip.  A successful
        frame ``reset()``s the schedule — the next episode starts at
        the base delay;
      - a ``{"moved": leader_addr}`` receipt (the standby's declared
        refusal) redials the quoted address IMMEDIATELY — the receipt
        is a resolution, not a failure;
      - every leader response carries the fenced lease generation
        (``gen``); a response whose generation is BELOW the largest
        this client has seen is a deposed leader's late ack — rejected
        (``stale_acks_rejected``) and the call re-delivered to the real
        leader, where the gateway's dedup-by-watermark trims the replay
        idempotently (never double-counted);
      - the retried call re-sends the SAME frame (same buffered chunks,
        same per-chunk stream offsets), so the resumed delivery starts
        exactly where the workers' ``watermark(sid)`` says it should:
        rows below it are trimmed at the edge, rows above it land once
        — bit-identical to an unbroken run.

    Failover observability rides the client: ``reconnects``,
    ``moved_receipts``, ``redial_delays_ms`` (the pinnable backoff
    schedule), ``last_failover_ms`` (first disconnect to first
    successful call) and ``resumed`` (sessions whose delivery resumed
    after at least one reconnect).
    """

    def __init__(
        self,
        addrs,
        *,
        tenant: str | None = None,
        deadline_s: float = 10.0,
        retries: int = 2,
        reconnect: BackoffPolicy | None = None,
        seed: int = 0,
        sleep=None,
        max_attempts: int = 240,
    ):
        parsed = []
        for a in addrs:
            if isinstance(a, str):
                host, _, port = a.rpartition(":")
                parsed.append((host, int(port)))
            else:
                parsed.append((a[0], int(a[1])))
        if not parsed:
            raise ValueError("need at least one gateway address")
        self.addrs = parsed
        self._addr_i = 0
        self._reconnect = Backoff(
            reconnect
            or BackoffPolicy(base_ms=10.0, cap_ms=500.0, factor=2.0,
                             jitter=0.25),
            seed=seed,
        )
        self._sleep_fn = sleep if sleep is not None else time.sleep
        self._max_attempts = int(max_attempts)
        self.gen = 0
        self.reconnects = 0
        self.moved_receipts = 0
        self.stale_acks_rejected = 0
        self.failover_episodes = 0
        self.redial_delays_ms: list = []
        self.resumed: set = set()
        self.last_failover_ms: float | None = None
        self._episode_t0: float | None = None
        self._episodes_settled = 0
        host, port = parsed[0]
        super().__init__(
            host, port, tenant=tenant, deadline_s=deadline_s,
            retries=retries,
        )

    # ------------------------------------------------------- transport

    def _disconnected(self) -> None:
        """One failed dial/call: start (or continue) a failover
        episode, wait out the next backoff delay, rotate to the next
        configured address and re-dial."""
        self.reconnects += 1
        if self._episode_t0 is None:
            self._episode_t0 = time.monotonic()
        delay_ms = self._reconnect.next_ms()
        self.redial_delays_ms.append(delay_ms)
        self._sleep_fn(delay_ms / 1e3)
        self._addr_i = (self._addr_i + 1) % len(self.addrs)
        host, port = self.addrs[self._addr_i]
        self._dial(host, port)

    def _retarget(self, addr) -> None:
        """Follow a ``{"moved": leader_addr}`` receipt.  A receipt with
        no address (election still in flight) degrades to the rotate-
        under-backoff path."""
        if self._episode_t0 is None:
            self._episode_t0 = time.monotonic()
        if not addr:
            self._disconnected()
            return
        host, _, port = str(addr).rpartition(":")
        for i, (h, p) in enumerate(self.addrs):
            if h == host and p == int(port):
                self._addr_i = i
                break
        self._dial(host, int(port))

    def _call(self, method: str, meta: dict | None = None,
              payload: bytes = b""):
        attempts = 0
        while True:
            try:
                resp, p = self._client.call(method, meta, payload)
            except (RpcConnectionRefused, RpcDeadlineExceeded):
                attempts += 1
                if attempts > self._max_attempts:
                    raise
                self._disconnected()
                continue
            if isinstance(resp, dict) and "moved" in resp:
                self.moved_receipts += 1
                attempts += 1
                if attempts > self._max_attempts:
                    raise RpcConnectionRefused(
                        "no gateway leader after "
                        f"{self._max_attempts} attempts"
                    )
                self._retarget(resp.get("moved"))
                continue
            g = resp.get("gen") if isinstance(resp, dict) else None
            if g is not None:
                g = int(g)
                if g < self.gen:
                    # a deposed leader's late ack: its mandate is
                    # fenced out — reject the receipt and re-deliver
                    # to the real leader (edge dedup-by-watermark
                    # makes the replay idempotent)
                    self.stale_acks_rejected += 1
                    attempts += 1
                    if attempts > self._max_attempts:
                        raise RpcConnectionRefused(
                            "only stale gateway generations answered"
                        )
                    self._disconnected()
                    continue
                self.gen = g
            # a successful frame ends the episode: backoff restarts at
            # the base delay (no thundering herd carried forward)
            self._reconnect.reset()
            if self._episode_t0 is not None:
                self.last_failover_ms = (
                    time.monotonic() - self._episode_t0
                ) * 1e3
                self._episode_t0 = None
                self.failover_episodes += 1
            return resp, p

    # ------------------------------------------------- resume tracking

    def _flush_pending(self) -> None:
        sids = [sid for sid, _, _ in self._pending]
        super()._flush_pending()
        if self.failover_episodes > self._episodes_settled:
            # this frame is the first to land after a failover episode
            # (socket loss OR a moved-receipt retarget): its sessions
            # RESUMED across the lease flip
            self.resumed.update(sids)
            self._episodes_settled = self.failover_episodes
