"""Edge admission for the ingest gateway — shed from the header.

The gateway sits between untrusted client connections and the fleet's
workers.  Its admission control runs BEFORE a frame's payload is
assembled or decoded (``FrameBuffer.peek_header`` /
``FrameBuffer.skip_frame``): the batched push frame's header already
carries everything a shed decision needs —

  - ``s``     the session count in the frame (chunk-batch codec);
  - the declared payload byte length (the frame's own length field);
  - ``wm``    the client's sample watermark — how far its stream has
              advanced; a frame whose watermark lags the newest one
              seen on the connection is STALE traffic (a catch-up
              replay of data whose scoring window has passed).

A refused frame costs the edge exactly one header parse: no payload
bytes object, no numpy array, no arena reservation, no worker RPC.
The refusal is DECLARED — the client gets a ``{"shed": reason}``
response addressed to its request id and keeps its delivery cursors,
so every sample it sent is either refused-with-a-receipt at the edge
or lands in the fleet's window accounting.  Zero undeclared drops is
the test-pinned contract.

The shed LADDER mirrors the engine's own (pressure escalates, recovery
de-escalates), driven by the gateway's outstanding-window backlog:

  level 0  (backlog < soft_backlog)   admit everything within the
           static bounds (frame sessions / bytes / max staleness);
  level 1  (backlog >= soft_backlog)  additionally refuse ANY frame
           whose watermark lags the connection's newest — under
           pressure, stale catch-up traffic is the first to go;
  level 2  (backlog >= hard_backlog)  refuse every push frame until
           the backlog drains — the queue, not the allocator, is the
           thing being protected.

Engine-free by design: this module imports nothing from the serving
engine, so the gateway's admission path stays importable (and
testable) without a jax backend behind it.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """Edge-admission bounds.  Defaults are sized for the loopback
    smoke fleets; a production gateway tunes them to its workers'
    ``max_queue_windows``."""

    # backlog ladder thresholds, in outstanding (enqueued-but-not-yet-
    # returned) windows across the fleet the gateway fronts
    soft_backlog: int = 4096
    hard_backlog: int = 16384
    # static per-frame bounds, enforceable at any ladder level
    max_frame_sessions: int = 4096
    # a SOFT byte ceiling below wire.MAX_FRAME_BYTES: past the wire
    # ceiling the connection dies (protocol violation); past this one
    # the frame is shed with a receipt (a well-formed but oversized
    # burst)
    max_frame_bytes: int = 8 << 20
    # how many samples a frame's watermark may lag the connection's
    # newest before it is stale (level 0; level 1 tightens this to 0)
    max_watermark_lag: int = 4096


class EdgeAdmission:
    """The gateway's shed ladder + its accounting.

    ``admit(meta, payload_len)`` returns ``None`` to admit or a shed
    reason string; it reads ONLY the frame header.  The backlog the
    ladder rides is the gateway's own estimate — ``note_enqueued`` on
    every admitted round's enqueued windows, ``note_retired`` on every
    event returned — resynced to the fleet's true pending count
    whenever the gateway reads ``accounting()`` (engine-side declared
    sheds shrink the real backlog without passing through the gateway).
    """

    def __init__(self, config: IngestConfig | None = None):
        self.config = config or IngestConfig()
        self.backlog = 0
        self.latest_wm = 0
        self.admitted_frames = 0
        self.admitted_sessions = 0
        self.admitted_bytes = 0
        self.shed_frames = 0
        self.shed_sessions = 0
        self.shed_bytes = 0
        self.shed_by_reason: dict[str, int] = {}

    # ------------------------------------------------------- pressure

    @property
    def level(self) -> int:
        if self.backlog >= self.config.hard_backlog:
            return 2
        if self.backlog >= self.config.soft_backlog:
            return 1
        return 0

    def note_enqueued(self, n_windows: int) -> None:
        self.backlog += int(n_windows)

    def note_retired(self, n_events: int) -> None:
        self.backlog = max(0, self.backlog - int(n_events))

    def resync_backlog(self, pending: int) -> None:
        """Pin the estimate to the fleet's true pending count (from
        ``accounting()``): engine-side declared sheds retire windows
        the gateway never sees come back as events."""
        self.backlog = max(0, int(pending))

    # ------------------------------------------------------ admission

    def admit(self, meta: dict, payload_len: int) -> str | None:
        """Header-only admission for one batched push frame.  The
        ladder's checks run cheapest-first; the FIRST breached bound
        names the shed (one declared reason per refused frame)."""
        cfg = self.config
        sessions = int(meta.get("s", 0))
        wm = int(meta.get("wm", self.latest_wm))
        reason = None
        if sessions > cfg.max_frame_sessions:
            reason = "frame_sessions"
        elif payload_len > cfg.max_frame_bytes:
            reason = "frame_bytes"
        elif self.level >= 2:
            reason = "hard_backlog"
        else:
            lag = self.latest_wm - wm
            allowed = 0 if self.level >= 1 else cfg.max_watermark_lag
            if lag > allowed:
                reason = "stale" if self.level == 0 else "soft_backlog"
        if reason is not None:
            self.shed_frames += 1
            self.shed_sessions += sessions
            self.shed_bytes += int(payload_len)
            self.shed_by_reason[reason] = (
                self.shed_by_reason.get(reason, 0) + 1
            )
            return reason
        self.latest_wm = max(self.latest_wm, wm)
        self.admitted_frames += 1
        self.admitted_sessions += sessions
        self.admitted_bytes += int(payload_len)
        return None

    # ------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        return {
            "level": self.level,
            "backlog": self.backlog,
            "admitted_frames": self.admitted_frames,
            "admitted_sessions": self.admitted_sessions,
            "admitted_bytes": self.admitted_bytes,
            "shed_frames": self.shed_frames,
            "shed_sessions": self.shed_sessions,
            "shed_bytes": self.shed_bytes,
            "shed_by_reason": dict(self.shed_by_reason),
        }
