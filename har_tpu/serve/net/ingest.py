"""Edge admission for the ingest gateway — shed from the header.

The gateway sits between untrusted client connections and the fleet's
workers.  Its admission control runs BEFORE a frame's payload is
assembled or decoded (``FrameBuffer.peek_header`` /
``FrameBuffer.skip_frame``): the batched push frame's header already
carries everything a shed decision needs —

  - ``s``     the session count in the frame (chunk-batch codec);
  - the declared payload byte length (the frame's own length field);
  - ``wm``    the client's sample watermark — how far its stream has
              advanced; a frame whose watermark lags the newest one
              seen on the connection is STALE traffic (a catch-up
              replay of data whose scoring window has passed);
  - ``tn``    the client/tenant identity.  When the gateway is
              configured with a tenant table, a frame whose tenant is
              missing or unknown is a PROTOCOL VIOLATION, not a shed:
              the connection hangs up with no receipt and no ledger
              trace (``TenantViolation`` — the same fate as a CRC
              mismatch; an unauthenticated sender learns nothing).

A refused frame costs the edge exactly one header parse: no payload
bytes object, no numpy array, no arena reservation, no worker RPC.
The refusal is DECLARED — the client gets a ``{"shed": reason}``
response addressed to its request id and keeps its delivery cursors,
so every sample it sent is either refused-with-a-receipt at the edge
or lands in the fleet's window accounting.  Zero undeclared drops is
the test-pinned contract.

The shed LADDER mirrors the engine's own (pressure escalates, recovery
de-escalates) and is walked PER TENANT: each tenant's thresholds are
its weighted fair share of the gateway's backlog budget
(``weight / sum(weights)`` of ``soft_backlog`` / ``hard_backlog``), and
the ladder judges the tenant's OWN backlog contribution against them —

  level 0  (tenant backlog < its soft share)   admit everything within
           the static bounds (frame sessions / bytes / max staleness);
  level 1  (tenant backlog >= its soft share)  additionally refuse ANY
           frame whose watermark lags the tenant's newest — under
           pressure, stale catch-up traffic is the first to go;
  level 2  (tenant backlog >= its hard share)  refuse every push frame
           from that tenant until its backlog drains.

Weighted fairness falls out of the shares: a storming tenant crosses
ITS OWN hard share while a quiet protected tenant (the paper's
monitored-patient cohort, weighted high) stays at level 0 — the storm
is shed before the quiet tenant ever sees backpressure, and the sum of
all shares caps the total backlog at exactly the old global bound.
With no tenant table (single-tenant mode) every frame lands on one
default slice whose share is 1.0 — bit-identical to the pre-tenant
ladder.

The ledger (``snapshot()``) carries a per-tenant slice beside the
globals; the slices sum to the global counters in every snapshot, so
the edge conservation law holds per tenant and in total.

Engine-free by design: this module imports nothing from the serving
engine (``wire`` is the frame codec, itself engine-free), so the
gateway's admission path stays importable (and testable) without a
jax backend behind it.
"""

from __future__ import annotations

import dataclasses
import math

from har_tpu.serve.net.wire import FrameError

# the slice unidentified traffic lands on when no tenant table is
# configured (single-tenant mode): one tenant, share 1.0, so the
# per-tenant ladder degenerates to the global one bit-identically
DEFAULT_TENANT = "default"


class TenantViolation(FrameError):
    """Missing/unknown tenant id on a data frame while a tenant table
    is configured: a protocol violation, not a shed — the server hangs
    up the connection with no receipt and no ledger trace (FrameError's
    fate in the RpcServer), exactly like a CRC mismatch."""


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """Edge-admission bounds.  Defaults are sized for the loopback
    smoke fleets; a production gateway tunes them to its workers'
    ``max_queue_windows``."""

    # backlog ladder thresholds, in outstanding (enqueued-but-not-yet-
    # returned) windows across the fleet the gateway fronts; each
    # tenant's ladder runs on its weighted share of these
    soft_backlog: int = 4096
    hard_backlog: int = 16384
    # static per-frame bounds, enforceable at any ladder level
    max_frame_sessions: int = 4096
    # a SOFT byte ceiling below wire.MAX_FRAME_BYTES: past the wire
    # ceiling the connection dies (protocol violation); past this one
    # the frame is shed with a receipt (a well-formed but oversized
    # burst)
    max_frame_bytes: int = 8 << 20
    # how many samples a frame's watermark may lag the connection's
    # newest before it is stale (level 0; level 1 tightens this to 0)
    max_watermark_lag: int = 4096
    # the tenant table: ((tenant_id, weight), ...).  Empty = identity
    # not enforced, everything accounted on the default slice.  A
    # higher weight is a larger fair share of the backlog budget — the
    # protected monitored-patient cohort rides a high weight
    tenants: tuple = ()


def _fresh_slice() -> dict:
    return {
        "backlog": 0,
        "latest_wm": 0,
        "admitted_frames": 0,
        "admitted_sessions": 0,
        "admitted_bytes": 0,
        "shed_frames": 0,
        "shed_sessions": 0,
        "shed_bytes": 0,
        "shed_by_reason": {},
    }


class EdgeAdmission:
    """The gateway's per-tenant shed ladder + its accounting.

    ``admit(meta, payload_len)`` returns ``None`` to admit or a shed
    reason string (raising ``TenantViolation`` for unidentified frames
    when a tenant table is configured); it reads ONLY the frame header.
    The backlog each ladder rides is the gateway's own estimate —
    ``note_enqueued`` on every admitted round's enqueued windows,
    ``note_retired`` on every event returned, both tenant-attributed —
    resynced to the fleet's true pending count whenever the gateway
    reads ``accounting()`` (engine-side declared sheds shrink the real
    backlog without passing through the gateway).

    ``stats`` (optionally a ``FleetStats``) receives the per-tenant
    accept/shed counters (``note_tenant_accept`` / ``note_tenant_shed``)
    so the fleet's persisted observability carries the edge's identity
    axis too.
    """

    def __init__(self, config: IngestConfig | None = None, *, stats=None):
        self.config = config or IngestConfig()
        self.stats = stats
        self.tenants = {
            str(t): float(w) for t, w in (self.config.tenants or ())
        }
        total = sum(self.tenants.values())
        self._share = {
            t: (w / total if total > 0 else 1.0)
            for t, w in self.tenants.items()
        }
        self.backlog = 0
        self.latest_wm = 0
        self.admitted_frames = 0
        self.admitted_sessions = 0
        self.admitted_bytes = 0
        self.shed_frames = 0
        self.shed_sessions = 0
        self.shed_bytes = 0
        self.shed_by_reason: dict[str, int] = {}
        self._per_tenant: dict[str, dict] = {}

    # -------------------------------------------------------- identity

    def resolve_tenant(self, meta: dict) -> str:
        """The frame's tenant id, validated against the table.  Without
        a table, identity is not enforced (missing id lands on the
        default slice); with one, an absent or unknown id raises
        ``TenantViolation`` — the RpcServer hangs the connection up
        with no receipt."""
        tid = meta.get("tn")
        if not self.tenants:
            return DEFAULT_TENANT if tid is None else str(tid)
        if tid is None or str(tid) not in self.tenants:
            raise TenantViolation(f"unknown tenant {tid!r}")
        return str(tid)

    def _slice(self, tenant: str) -> dict:
        s = self._per_tenant.get(tenant)
        if s is None:
            s = self._per_tenant[tenant] = _fresh_slice()
        return s

    def _thresholds(self, tenant: str) -> tuple[int, int]:
        """(soft, hard) for this tenant: its weighted fair share of the
        global budget, never below one window (a zero-share ladder
        would refuse a tenant's very first frame)."""
        share = self._share.get(tenant, 1.0)
        cfg = self.config
        return (
            max(1, math.ceil(cfg.soft_backlog * share)),
            max(1, math.ceil(cfg.hard_backlog * share)),
        )

    # ------------------------------------------------------- pressure

    @property
    def level(self) -> int:
        if self.backlog >= self.config.hard_backlog:
            return 2
        if self.backlog >= self.config.soft_backlog:
            return 1
        return 0

    def tenant_level(self, tenant: str) -> int:
        soft, hard = self._thresholds(tenant)
        backlog = self._slice(tenant)["backlog"]
        if backlog >= hard:
            return 2
        if backlog >= soft:
            return 1
        return 0

    def note_enqueued(self, n_windows: int, tenant: str | None = None) -> None:
        n = int(n_windows)
        self.backlog += n
        self._slice(tenant or DEFAULT_TENANT)["backlog"] += n

    def note_retired(self, n_events: int, tenant: str | None = None) -> None:
        n = int(n_events)
        self.backlog = max(0, self.backlog - n)
        ts = self._slice(tenant or DEFAULT_TENANT)
        ts["backlog"] = max(0, ts["backlog"] - n)

    def resync_backlog(self, pending: int) -> None:
        """Pin the estimate to the fleet's true pending count (from
        ``accounting()``): engine-side declared sheds retire windows
        the gateway never sees come back as events.  The per-tenant
        backlog estimates rescale proportionally — the fleet's pending
        count carries no tenant attribution, so the gateway's own
        attribution ratio is the best available prior."""
        pending = max(0, int(pending))
        total = sum(s["backlog"] for s in self._per_tenant.values())
        if total > 0:
            scaled = 0
            largest = max(
                self._per_tenant.values(), key=lambda s: s["backlog"]
            )
            for s in self._per_tenant.values():
                s["backlog"] = (s["backlog"] * pending) // total
                scaled += s["backlog"]
            largest["backlog"] += pending - scaled
        self.backlog = pending

    # ------------------------------------------------------ admission

    def admit(self, meta: dict, payload_len: int) -> str | None:
        """Header-only admission for one batched push frame, judged on
        the frame's TENANT ladder.  The checks run cheapest-first; the
        FIRST breached bound names the shed (one declared reason per
        refused frame)."""
        cfg = self.config
        tenant = self.resolve_tenant(meta)
        ts = self._slice(tenant)
        sessions = int(meta.get("s", 0))
        wm = int(meta.get("wm", ts["latest_wm"]))
        tlevel = self.tenant_level(tenant)
        reason = None
        if sessions > cfg.max_frame_sessions:
            reason = "frame_sessions"
        elif payload_len > cfg.max_frame_bytes:
            reason = "frame_bytes"
        elif tlevel >= 2:
            reason = "hard_backlog"
        else:
            lag = ts["latest_wm"] - wm
            allowed = 0 if tlevel >= 1 else cfg.max_watermark_lag
            if lag > allowed:
                reason = "stale" if tlevel == 0 else "soft_backlog"
        if reason is not None:
            self.shed_frames += 1
            self.shed_sessions += sessions
            self.shed_bytes += int(payload_len)
            self.shed_by_reason[reason] = (
                self.shed_by_reason.get(reason, 0) + 1
            )
            ts["shed_frames"] += 1
            ts["shed_sessions"] += sessions
            ts["shed_bytes"] += int(payload_len)
            ts["shed_by_reason"][reason] = (
                ts["shed_by_reason"].get(reason, 0) + 1
            )
            if self.stats is not None:
                self.stats.note_tenant_shed(tenant)
            return reason
        ts["latest_wm"] = max(ts["latest_wm"], wm)
        self.latest_wm = max(self.latest_wm, wm)
        self.admitted_frames += 1
        self.admitted_sessions += sessions
        self.admitted_bytes += int(payload_len)
        ts["admitted_frames"] += 1
        ts["admitted_sessions"] += sessions
        ts["admitted_bytes"] += int(payload_len)
        if self.stats is not None:
            self.stats.note_tenant_accept(tenant)
        return None

    # ------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """The edge ledger: globals plus a per-tenant slice.  The
        slices' admitted_* / shed_* counters sum to the globals in
        every snapshot — the conservation law holds per tenant and in
        total (test-pinned)."""
        return {
            "level": self.level,
            "backlog": self.backlog,
            "admitted_frames": self.admitted_frames,
            "admitted_sessions": self.admitted_sessions,
            "admitted_bytes": self.admitted_bytes,
            "shed_frames": self.shed_frames,
            "shed_sessions": self.shed_sessions,
            "shed_bytes": self.shed_bytes,
            "shed_by_reason": dict(self.shed_by_reason),
            "tenants": {
                t: {
                    **{
                        k: (dict(v) if isinstance(v, dict) else v)
                        for k, v in s.items()
                    },
                    "level": self.tenant_level(t),
                }
                for t, s in self._per_tenant.items()
            },
        }
