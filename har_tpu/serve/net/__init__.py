"""Real multi-host transport for the fleet cluster (PR 13).

The PR-7 control plane is architecturally multi-host but physically one
process: workers are in-process objects behind the ``ClusterWorker``
shim, the controller is a singleton, and the leases ride a FakeClock.
This package puts a real wire behind that seam:

  wire.py       length-prefixed CRC-framed messages over TCP — the SAME
                framing the write-ahead journal uses on disk
                (``journal.encode_record``), plus codecs for the
                payloads that already exist as journal records
                (session exports ride the ``adopt`` record layout,
                events the ``ack`` layout)
  rpc.py        request/response over a socket: deadlines, retry via
                ``utils.backoff``, connection-refused vs
                deadline-exceeded error taxonomy, duplicate-delivery
                dedup, deterministic link-fault injection
  worker.py     ``har serve-worker`` — one FleetServer + journal as an
                OS subprocess on a loopback socket, real monotonic
                clocks
  client.py     ``NetWorker`` — the transport-backed twin of
                ``ClusterWorker``: same surface, every call an RPC
  controller.py ``NetCluster`` — ``FleetCluster`` over NetWorkers
                (hand-offs ride the adopt RPC; with ship agents
                registered, failover is SHARED-NOTHING: the dead
                worker's journal ships over the wire into a private
                staging dir and is digest-verified before restore)
  ingest.py     edge admission for the front door: the shed ladder that
                judges a batched push frame from its HEADER (session
                count, byte length, staleness watermark) before any
                payload decode or allocation
  gateway.py    ``har serve-gateway`` — the fleet's ingest front door:
                clients speak the wire protocol to ONE gateway process
                which multiplexes batched push frames (one per delivery
                round) onto the workers, shedding at the edge with
                declared receipts
  ship.py       the journal-shipping RPC (``har serve-agent``): one
                agent per worker host streams journal dirs as chunked,
                manifest-digested, resumable transfers — the failover
                hand-off currency across a real process boundary
  tail.py       the ship protocol pointed at a MOVING target: resumable
                incremental pulls of a live worker's journal into a
                standby-local mirror (``har serve-agent --follow``
                rides this; see ``har_tpu.serve.replica`` for the warm
                in-memory replica kept on top of the tailed bytes)
  election.py   replicated controller: wall-clock lease file + fenced
                campaign; a replica completes ``takeover`` when the
                leader's lease expires
  chaos.py      the chaos matrix re-run over the wire + the
                partition-tolerance matrix (slow link, dropped probe,
                duplicated delivery, split brain)
  smoke.py      the release gate's ``wire_failover_smoke`` + the bench
                lane's measurement

See docs/multihost.md ("Wire protocol") for the frame layout, the
election rules and the partition-resolution argument.
"""

from har_tpu.serve.net.client import NetWorker
from har_tpu.serve.net.controller import (
    AgentHandle,
    NetCluster,
    launch_agents,
    launch_workers,
)
from har_tpu.serve.net.election import ControllerReplica, LeaderLease
from har_tpu.serve.net.gateway import (
    GatewayClient,
    IngestGateway,
    launch_gateway,
)
from har_tpu.serve.net.ingest import EdgeAdmission, IngestConfig
from har_tpu.serve.net.ship import (
    ShipAgent,
    ShipClient,
    ShipError,
    ShipUnavailable,
    fetch_journal,
)
from har_tpu.serve.net.rpc import (
    LinkFaults,
    RpcClient,
    RpcConnectionRefused,
    RpcDeadlineExceeded,
    RpcError,
    RpcRemoteError,
    RpcServer,
)
from har_tpu.serve.net.smoke import (
    replication_smoke,
    wire_failover_smoke,
)
from har_tpu.serve.net.tail import (
    LocalShipSource,
    finalize_tail,
    tail_once,
)
from har_tpu.serve.net.wire import (
    MAX_FRAME_BYTES,
    FrameBuffer,
    FrameError,
    decode_events,
    decode_export,
    encode_events,
    encode_export,
)

__all__ = [
    "AgentHandle",
    "ControllerReplica",
    "EdgeAdmission",
    "FrameBuffer",
    "FrameError",
    "GatewayClient",
    "IngestConfig",
    "IngestGateway",
    "LeaderLease",
    "LinkFaults",
    "LocalShipSource",
    "MAX_FRAME_BYTES",
    "NetCluster",
    "NetWorker",
    "RpcClient",
    "RpcConnectionRefused",
    "RpcDeadlineExceeded",
    "RpcError",
    "RpcRemoteError",
    "RpcServer",
    "ShipAgent",
    "ShipClient",
    "ShipError",
    "ShipUnavailable",
    "decode_events",
    "decode_export",
    "encode_events",
    "encode_export",
    "fetch_journal",
    "finalize_tail",
    "launch_agents",
    "launch_gateway",
    "launch_workers",
    "replication_smoke",
    "tail_once",
    "wire_failover_smoke",
]
