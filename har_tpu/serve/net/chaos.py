"""Chaos over the wire: the PR-7 kill matrix re-run with REAL process
deaths, plus the partition-tolerance matrix the in-process harness
could not express (a shared-memory shim has no slow links).

SHARED-NOTHING throughout (PR 14): every worker's journal lives in a
private per-host directory the controller never reads, with one
journal-ship agent per host (``net/ship.py``) — so every failover in
every cell exercises the ship RPC, and the matrix gains the ship axis
(``SHIP_KILL_POINTS``: the agent killed mid-send, the controller
killed mid-receive or post-verify).

Two matrices:

``run_net_kill_point`` — every engine stage boundary
(``chaos.KILL_POINTS``) killed inside ONE subprocess worker of a live
3-worker loopback cluster (``--chaos-point`` makes the worker
``os._exit`` there: a genuine SIGKILL, the un-flushed journal suffix
genuinely gone), plus the two controller points
(``chaos.CLUSTER_KILL_POINTS``: the CONTROLLER dies mid-migration, the
worker processes survive, ``NetCluster.takeover`` finishes the job).
The verdict is the same three-part cross-worker contract as the
in-process matrix — zero double-scored, migrated streams BIT-IDENTICAL
to the un-killed IN-PROCESS reference run, global conservation in
every observable snapshot — proving the wire changed nothing.

``run_net_partition`` — the failure modes only a real link has:

  ``slow_link``       one worker's calls exceed the deadline for a
                      while: the client retries (same request id,
                      server-side dedup = exactly-once), the prober
                      spends NO strike (``note_timeout``), and the
                      congested-but-alive worker is NOT failovered;
  ``dropped_probe``   blackholed requests: timeouts re-pace the probe
                      without a strike — again no spurious failover;
  ``duplicate``       every push delivered twice: the server's
                      request-id dedup answers the duplicate from
                      cache, zero double-ingested windows;
  ``split_brain``     a deposed controller crashes mid-hand-off
                      (adopt durable, evict not): dual LIVE ownership,
                      resolved by the session's ``handoffs``
                      generation when the next controller takes over —
                      a single surviving owner, zero windows lost.

Both matrices run on real monotonic time (no FakeClock — that is the
point), with small leases so the suite stays fast.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time

from har_tpu.serve.chaos import (
    CLUSTER_KILL_POINTS,
    GATEWAY_KILL_POINTS,
    KILL_POINTS,
    NET_PARTITION_CASES,
    SHIP_KILL_POINTS,
    _DEFAULT_AT,
    KillPlan,
    SimulatedCrash,
    _build_cluster,
    _cluster_schedule,
    _cluster_verdict,
    _event_fields,
    _recordings,
)
from har_tpu.serve.cluster.controller import ClusterConfig
from har_tpu.serve.cluster.membership import (
    WorkerTimeout,
    WorkerUnavailable,
)
from har_tpu.serve.cluster.router import ConsistentHashRouter
from har_tpu.serve.faults import FakeClock
from har_tpu.serve.loadgen import AnalyticDemoModel
from har_tpu.serve.net.controller import (
    NetCluster,
    launch_agents,
    launch_workers,
)

# failure detection tuned for a loopback suite: a dead process refuses
# instantly, so death lands within ~lease_s of the kill
_NET_CONFIG = dict(
    lease_s=0.4, probe_retries=2, probe_base_ms=20.0, probe_cap_ms=100.0
)

# ship pull granularity for the matrix: small enough that the smoke-
# scale journals span MANY chunks, so the mid_ship_* occurrences land
# genuinely mid-transfer (durable progress exists, transfer unfinished)
_MATRIX_CHUNK_BYTES = 4096


def _net_cluster_config() -> ClusterConfig:
    return ClusterConfig(**_NET_CONFIG)


def _launch_private_fleet(
    root: str,
    priv: str,
    workers: int,
    *,
    chaos_worker=None,
    chaos_point=None,
    chaos_at=1,
    agent_chaos_worker=None,
    agent_chaos_point=None,
    agent_chaos_at=1,
    **worker_kwargs,
):
    """The shared-nothing launch: each worker journals under its own
    private host directory ``<priv>/hK/wK`` (the controller never
    reads it), with one journal-ship agent per host serving it.
    Returns ``(net_workers, agent_handles)``."""
    net_workers = launch_workers(
        root, workers,
        journal_root=priv,
        chaos_worker=chaos_worker,
        chaos_point=chaos_point,
        chaos_at=chaos_at,
        **worker_kwargs,
    )
    roots = {
        w.worker_id: os.path.dirname(w.journal_dir)
        for w in net_workers
    }
    handles = launch_agents(
        roots,
        chaos_agent=agent_chaos_worker,
        chaos_point=agent_chaos_point,
        chaos_at=agent_chaos_at,
    )
    return net_workers, handles


def predicted_owner(session_id, workers: int, replicas: int | None = None):
    """The ring owner of a session BEFORE the cluster exists — the ring
    is deterministic in (worker ids, replicas), so the chaos victim
    (owner of session 0) is computable at worker-spawn time."""
    router = ConsistentHashRouter(
        replicas or ClusterConfig().replicas
    )
    for i in range(int(workers)):
        router.add_worker(f"w{i}")
    return router.owner(session_id)


def _drive_net_cluster(cluster, recordings, cursors, upto, hop, events,
                       on_round=None, max_rounds=20000, pace_s=0.002):
    """Real-time twin of ``chaos._drive_cluster``: hop-aligned
    round-robin delivery against a NetCluster.  A push that fails
    (refused OR timed out) keeps its cursor; a TIMED-OUT push is
    ambiguous (the worker may have executed it), so the cursor re-syncs
    from the owner's durable watermark before re-delivery — the
    documented transport contract, exercised for real here.  Completed
    migrations rewind their session's cursor to the adopted watermark;
    the loop keeps polling until no session is stranded on a dead
    worker."""
    for i in range(len(recordings)):
        try:
            cursors[i] = cluster.watermark(i)
        except WorkerUnavailable:
            pass  # mid-failover: the migration rewind below lands
    seen_migrations = len(cluster.migration_log)
    resync: set = set()
    for _ in range(max_rounds):
        active = False
        for i, rec in enumerate(recordings):
            stop = min(upto, len(rec))
            if i in resync:
                try:
                    cursors[i] = cluster.watermark(i)
                    resync.discard(i)
                except WorkerUnavailable:
                    continue  # still unreachable; keep the flag
            if cursors[i] >= stop:
                continue
            active = True
            take = hop - (cursors[i] % hop) or hop
            chunk = rec[cursors[i] : min(cursors[i] + take, stop)]
            try:
                cluster.push(i, chunk)
            except WorkerTimeout:
                # ambiguous delivery: the worker may hold these rows —
                # re-sync from its watermark before pushing more
                resync.add(i)
                continue
            except WorkerUnavailable:
                continue  # cursor kept; re-delivered post-failover
            cursors[i] += len(chunk)
        events.extend(cluster.poll(force=True))
        if on_round is not None:
            on_round(cluster)
        while seen_migrations < len(cluster.migration_log):
            sid = cluster.migration_log[seen_migrations]["sid"]
            cursors[sid] = cluster.watermark(sid)
            seen_migrations += 1
        if not active:
            # convergence is judged on the DURABLE watermark, not the
            # cursor: a worker can accept a push and die before its
            # records reach disk, and the controller only learns at
            # detection time (over a real wire there is no synchronous
            # `alive` bit).  An unreachable owner means a failover is
            # pending (keep polling — the polls feed the detector);
            # a watermark short of the schedule means the adopted copy
            # needs re-delivery from there — the documented transport
            # contract, exercised for real
            stranded = rewound = False
            for i in range(len(recordings)):
                stop = min(upto, len(recordings[i]))
                try:
                    w = cluster.watermark(i)
                except WorkerUnavailable:
                    stranded = True
                    continue
                if w < stop:
                    cursors[i] = w
                    rewound = True
            if bool(resync) or stranded or rewound:
                pass  # not settled yet
            else:
                break
        time.sleep(pace_s)  # real time IS the clock here
    else:  # pragma: no cover - harness guard
        raise RuntimeError("net cluster drive did not converge")
    events.extend(cluster.flush())
    if on_round is not None:
        on_round(cluster)


def _net_schedule(cluster, recordings, cursors, *, hop, swap_sample,
                  events, on_round=None):
    """The wire twin of ``chaos._cluster_schedule``: deliver to the
    swap point, resize every worker to 48 (the mid-run elastic bump
    the reference schedule applies), broadcast the hot swap, deliver
    the rest.  Idempotent per worker like the in-process schedule — a
    post-takeover resumption re-issues only where nothing landed."""
    _drive_net_cluster(
        cluster, recordings, cursors, swap_sample, hop, events, on_round
    )
    for w in list(cluster._workers.values()):
        if not w.alive:
            continue
        try:
            w.resize(48)
        except WorkerUnavailable:
            pass  # dead mid-broadcast: lands after failover via replay
    cluster.swap_model(None, version="B")
    _drive_net_cluster(
        cluster, recordings, cursors, max(map(len, recordings)), hop,
        events, on_round,
    )


def _safe_accounting(cluster, log: list) -> None:
    """Per-round conservation snapshot; a worker inside its suspicion
    window is unobservable over a real wire (its partition answers
    nobody), so those rounds record no snapshot instead of a fake one."""
    try:
        log.append(cluster.accounting())
    except WorkerUnavailable:
        pass


def run_net_kill_point(
    point: str,
    *,
    at: int | None = None,
    workers: int = 3,
    sessions: int = 12,
    seed: int = 0,
    n_samples: int = 300,
    window: int = 100,
    hop: int = 50,
    flush_every: int = 512,
    snapshot_every: int = 40,
    kill_round: int = 3,
) -> dict:
    """One cell of the wire chaos matrix (see module docstring).

    The reference is an IN-PROCESS un-killed cluster run of the same
    schedule (FakeClock, no fault hooks) — the acceptance bar is that
    the wire run's migrated streams are bit-identical to it.

    SHARED-NOTHING throughout: every worker's journal lives in a
    private per-host directory the controller never reads; failover
    journals arrive via the ship RPC from the host's agent process.
    The ship-axis points (``SHIP_KILL_POINTS``) additionally kill the
    transfer itself: the victim worker is really SIGKILLed mid-run,
    and then either the sending agent dies mid-ship (``mid_ship_send``
    — the harness restarts it, modeling a host daemon restart, and the
    parked failover resumes from the last durable chunk), the
    controller dies between chunks (``mid_ship_recv`` — takeover
    resumes the staged transfer), or the controller dies after the
    verified ship lands (``post_ship_pre_drain`` — takeover restores
    the complete staged copy)."""
    if (
        point not in KILL_POINTS
        and point not in CLUSTER_KILL_POINTS
        and point not in SHIP_KILL_POINTS
    ):
        raise ValueError(f"unknown net kill point {point!r}")
    at = _DEFAULT_AT[point] if at is None else at
    recordings = _recordings(sessions, n_samples, 3, seed)
    models = {"A": AnalyticDemoModel(), "B": AnalyticDemoModel(tau=5.0)}

    def loader(ver):
        return models.get(ver, models["A"])

    swap_sample = (n_samples // hop // 2) * hop

    # ---- reference: the un-killed IN-PROCESS cluster run ------------
    ref_root = tempfile.mkdtemp(prefix="har_netref_")
    try:
        ref_clock = FakeClock()
        ref = _build_cluster(
            ref_root, ref_clock, sessions=sessions, workers=workers,
            window=window, hop=hop, model=models["A"],
            flush_every=flush_every, snapshot_every=snapshot_every,
            loader=loader,
        )
        # the wire workers run without injected dispatch stalls; strip
        # the reference's fault hooks so both runs share one schedule
        for w in ref._workers.values():
            w.server._fault_hook = None
        for i in range(sessions):
            ref.add_session(i)
        ref_events: list = []
        _cluster_schedule(
            ref, recordings, [0] * sessions, hop=hop, clock=ref_clock,
            models=models, swap_sample=swap_sample, events=ref_events,
        )
        ref.close()
    finally:
        shutil.rmtree(ref_root, ignore_errors=True)

    # ---- the wire run -----------------------------------------------
    victim = predicted_owner(0, workers)
    root = tempfile.mkdtemp(prefix="har_netchaos_")
    priv = tempfile.mkdtemp(prefix="har_netpriv_")
    procs: dict = {}
    agent_procs: dict = {}
    try:
        net_workers, handles = _launch_private_fleet(
            root, priv, workers, window=window, hop=hop,
            target_batch=32, max_delay_ms=0.0, retries=1,
            flush_every=flush_every, snapshot_every=snapshot_every,
            chaos_worker=victim if point in KILL_POINTS else None,
            chaos_point=point if point in KILL_POINTS else None,
            chaos_at=at,
            agent_chaos_worker=(
                victim if point == "mid_ship_send" else None
            ),
            agent_chaos_point=(
                point if point == "mid_ship_send" else None
            ),
            agent_chaos_at=at,
        )
        procs.update({w.worker_id: w.process for w in net_workers})
        agent_procs.update(
            {wid: h.process for wid, h in handles.items()}
        )
        cluster = NetCluster(
            models["A"], root, _workers=net_workers,
            config=_net_cluster_config(), loader=loader,
            agents={wid: h.client() for wid, h in handles.items()},
            ship_chunk_bytes=_MATRIX_CHUNK_BYTES,
        )
        for i in range(sessions):
            cluster.add_session(i)
        events: list = []
        cursors = [0] * sessions
        balance_log: list = []
        rounds = {"n": 0}
        restarted = {"agent": False}
        controller_points = CLUSTER_KILL_POINTS + (
            "mid_ship_recv", "post_ship_pre_drain",
        )
        if point in controller_points:
            cluster.chaos = KillPlan(point, at)

        def on_round(c):
            rounds["n"] += 1
            if (
                (point in CLUSTER_KILL_POINTS
                 or point in SHIP_KILL_POINTS)
                and rounds["n"] == kill_round
            ):
                # a REAL worker death starts the failover the chosen
                # point then kills (the controller, or the transfer)
                procs[victim].kill()
            if (
                point == "mid_ship_send"
                and agent_procs[victim].poll() is not None
                and not restarted["agent"]
            ):
                # the sending host's agent died at its chunk boundary
                # (os._exit 137).  Restart it — a host daemon coming
                # back — and re-register: the parked failover retries
                # at the next poll and RESUMES from the last durable
                # chunk, never from scratch.
                restarted["agent"] = True
                fresh = launch_agents(
                    {victim: handles[victim].root}
                )[victim]
                handles[victim] = fresh
                agent_procs[victim] = fresh.process
                c.register_agent(victim, fresh.client())
            _safe_accounting(c, balance_log)

        crashed = False
        pre_crash_rpc = None
        t0 = time.perf_counter()
        try:
            _net_schedule(
                cluster, recordings, cursors, hop=hop,
                swap_sample=swap_sample, events=events,
                on_round=on_round,
            )
        except SimulatedCrash:
            crashed = True
            # the dead controller's transport evidence (bytes it
            # shipped before dying) — the takeover's counters restart
            # at zero, but the matrix judges the WHOLE failover
            pre_crash_rpc = cluster.transport_stats()
        fired = (
            procs[victim].poll() is not None
            if point in KILL_POINTS
            else restarted["agent"]
            if point == "mid_ship_send"
            else crashed
        )
        if not fired:
            cluster.shutdown_workers()
            cluster.close()
            return {
                "ok": False, "point": point,
                "why": f"kill point {point!r} never fired (at={at})",
                "windows_lost": 0, "failover_ms": 0.0,
            }
        if crashed:
            # the controller died mid-migration (or mid-ship); its
            # worker processes did not.  A fresh controller adopts the
            # still-responsive workers, resumes any half-shipped staged
            # transfer, and completes the orphaned failover — the
            # election layer drives exactly this via the lease file
            survivors = [
                w for w in cluster._workers.values() if w.alive
            ]
            cluster = NetCluster.takeover(
                models["A"], root, survivors,
                config=_net_cluster_config(), loader=loader,
                agents={
                    wid: h.client() for wid, h in handles.items()
                },
                ship_chunk_bytes=_MATRIX_CHUNK_BYTES,
            )
            _net_schedule(
                cluster, recordings, cursors, hop=hop,
                swap_sample=swap_sample, events=events,
                on_round=lambda c: _safe_accounting(c, balance_log),
            )
        failover_ms = (time.perf_counter() - t0) * 1e3
        stats = cluster.cluster_stats()
        verdict = _cluster_verdict(
            point, ref_events, events, cluster, balance_log, stats,
            failover_ms,
        )
        verdict["transport"] = "tcp"
        verdict["rpc"] = cluster.transport_stats()
        shipped = verdict["rpc"]["shipped_bytes"]
        resumes = verdict["rpc"]["ship_resumes"]
        chunks = verdict["rpc"]["ship_chunks"]
        if pre_crash_rpc is not None:
            shipped += pre_crash_rpc["shipped_bytes"]
            resumes += pre_crash_rpc["ship_resumes"]
            chunks += pre_crash_rpc["ship_chunks"]
        verdict["shipped_bytes"] = shipped
        verdict["ship_chunks"] = chunks
        verdict["ship_resumes"] = resumes
        if verdict["ok"] and shipped <= 0:
            verdict["ok"] = False
            verdict["why"] = (
                "failover completed without shipping any journal "
                "bytes — the shared-nothing path was bypassed"
            )
        if (
            verdict["ok"]
            and point in ("mid_ship_send", "mid_ship_recv")
            and resumes < 1
        ):
            verdict["ok"] = False
            verdict["why"] = (
                f"{point} fired but no transfer RESUMED from durable "
                "chunks — the ship restarted from scratch"
            )
        cluster.shutdown_workers()
        cluster.close()
        return verdict
    finally:
        # never leak worker/agent processes or rmtree under live
        # writers (clean exits already reaped: kill no-ops there)
        for proc in list(procs.values()) + list(agent_procs.values()):
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)
        shutil.rmtree(priv, ignore_errors=True)


# ------------------------------------------------------- partitions


def run_net_partition(
    case: str,
    *,
    workers: int = 3,
    sessions: int = 9,
    seed: int = 0,
    n_samples: int = 200,
    window: int = 100,
    hop: int = 50,
) -> dict:
    """One cell of the partition-tolerance matrix (module docstring).
    Every case must end with a single surviving owner per session,
    conservation balanced, and ``windows_lost == 0``."""
    if case not in NET_PARTITION_CASES:
        raise ValueError(f"unknown partition case {case!r}")
    if case == "split_brain":
        return _run_split_brain(
            workers=workers, sessions=sessions, seed=seed,
            n_samples=n_samples, window=window, hop=hop,
        )
    from har_tpu.serve.net.rpc import LinkFaults

    recordings = _recordings(sessions, n_samples, 3, seed)
    model = AnalyticDemoModel()
    victim = predicted_owner(0, workers)
    root = tempfile.mkdtemp(prefix="har_netpart_")
    priv = tempfile.mkdtemp(prefix="har_netpartpriv_")
    procs: list = []
    try:
        # private per-worker journal dirs here too: the partition
        # matrix must prove its zero-failover verdicts without any
        # shared-disk escape hatch (no agents needed — no partition
        # case restores a journal)
        net_workers = launch_workers(
            root, workers, window=window, hop=hop,
            target_batch=32, max_delay_ms=0.0,
            deadline_s=0.3, probe_deadline_s=0.2,
            journal_root=priv,
        )
        procs.extend(w.process for w in net_workers)
        cluster = NetCluster(
            model, root, _workers=net_workers,
            config=_net_cluster_config(),
            loader=lambda ver: model,
        )
        for i in range(sessions):
            cluster.add_session(i)
        # the link degrades MID-RUN (after admission): the impairment
        # must hit a working cluster, not its setup
        faults = None
        if case == "slow_link":
            # the victim's next 3 calls blow the deadline (the peer
            # still executes them: the retry-dedup path)
            faults = LinkFaults("delay", method="", times=3)
        elif case == "dropped_probe":
            faults = LinkFaults("drop", method="", times=3)
        elif case == "duplicate":
            faults = LinkFaults("dup", method="push", times=10**9)
        for w in net_workers:
            if w.worker_id == victim:
                w._client.faults = faults
        events: list = []
        cursors = [0] * sessions
        balance_log: list = []
        _drive_net_cluster(
            cluster, recordings, cursors, n_samples, hop, events,
            on_round=lambda c: _safe_accounting(c, balance_log),
        )
        why = _partition_verdict(
            cluster, events, balance_log, sessions, n_samples, window,
            hop, expect_failovers=0,
        )
        out = {
            "ok": why is None,
            "case": case,
            "why": why,
            "failovers": cluster.failovers,
            "rpc": cluster.transport_stats(),
            "delivered": len(events),
            "accounting": cluster.accounting(),
        }
        cluster.shutdown_workers()
        cluster.close()
        return out
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)
        shutil.rmtree(priv, ignore_errors=True)


def _partition_verdict(cluster, events, balance_log, sessions,
                       n_samples, window, hop, *, expect_failovers):
    """Shared checks: exactly-once delivery, complete delivery (the
    deterministic per-session window count), single live owner per
    session, conservation balanced in every observed snapshot."""
    keys = [(e.session_id, e.event.t_index) for e in events]
    if len(keys) != len(set(keys)):
        return "an event was delivered twice"
    expected = sessions * ((n_samples - window) // hop + 1)
    lost = expected - len(keys)
    if lost:
        return f"{lost} window(s) lost ({len(keys)}/{expected})"
    owners: dict = {}
    for sid in range(sessions):
        holding = [
            wid
            for wid, w in cluster._workers.items()
            if w.owns(sid)
        ]
        if len(holding) != 1:
            return (
                f"session {sid} owned by {holding!r} — not exactly one "
                "surviving owner"
            )
        owners[sid] = holding[0]
    acct = cluster.accounting()
    if not acct["balanced"] or acct["pending"] != 0:
        return f"conservation violated at the end: {acct}"
    for i, snap in enumerate(balance_log):
        if not snap["balanced"]:
            return f"conservation violated at snapshot {i}: {snap}"
    if cluster.failovers != expect_failovers:
        return (
            f"{cluster.failovers} failover(s) — expected "
            f"{expect_failovers} (a partition is not a death)"
        )
    return None


def _run_split_brain(*, workers, sessions, seed, n_samples, window,
                     hop) -> dict:
    """Split brain: controller A (the deposed leader) crashes inside a
    planned hand-off — the adopt is durable on the target, the evict
    never ran on the source — leaving the session LIVE ON TWO WORKERS.
    Controller B takes over and must resolve to a single owner by the
    ``handoffs`` generation (the adopted copy wins), then finish the
    run with zero windows lost."""
    recordings = _recordings(sessions, n_samples, 3, seed)
    model = AnalyticDemoModel()
    root = tempfile.mkdtemp(prefix="har_netsplit_")
    priv = tempfile.mkdtemp(prefix="har_netsplitpriv_")
    procs: list = []
    try:
        net_workers = launch_workers(
            root, workers, window=window, hop=hop,
            target_batch=32, max_delay_ms=0.0,
            journal_root=priv,
        )
        procs.extend(w.process for w in net_workers)
        cluster = NetCluster(
            model, root, _workers=net_workers,
            config=_net_cluster_config(),
            loader=lambda ver: model,
        )
        for i in range(sessions):
            cluster.add_session(i)
        events: list = []
        cursors = [0] * sessions
        half = (n_samples // hop // 2) * hop
        _drive_net_cluster(
            cluster, recordings, cursors, half, hop, events
        )
        # controller A: planned migration of session 0, killed at the
        # dual-ownership boundary (adopt durable, evict pending)
        src = cluster.worker_of(0)
        target = next(
            wid for wid in cluster._workers if wid != src
        )
        plan = KillPlan("mid_handoff", 1)
        cluster.chaos = plan
        crashed = False
        try:
            cluster.migrate_session(0, target)
        except SimulatedCrash:
            crashed = True
        if not crashed:
            cluster.shutdown_workers()
            cluster.close()
            return {
                "ok": False, "case": "split_brain",
                "why": "mid_handoff never fired",
            }
        dual = [
            wid
            for wid, w in cluster._workers.items()
            if w.owns(0)
        ]
        # controller B: the next lease generation — fresh clients to
        # the same workers; placement re-derived from actual ownership
        survivors = [w for w in cluster._workers.values() if w.alive]
        cluster2 = NetCluster.takeover(
            model, root, survivors,
            config=_net_cluster_config(),
            loader=lambda ver: model,
        )
        resolved_owner = cluster2.worker_of(0)
        balance_log: list = []
        _drive_net_cluster(
            cluster2, recordings, cursors, n_samples, hop, events,
            on_round=lambda c: _safe_accounting(c, balance_log),
        )
        why = _partition_verdict(
            cluster2, events, balance_log, sessions, n_samples,
            window, hop, expect_failovers=0,
        )
        if why is None and len(dual) != 2:
            why = (
                f"mid_handoff crash left session 0 on {dual!r}, "
                "not two workers — the split never happened"
            )
        if why is None and resolved_owner != target:
            why = (
                f"generation resolution kept {resolved_owner!r}, not "
                f"the adopter {target!r} (higher handoffs generation)"
            )
        out = {
            "ok": why is None,
            "case": "split_brain",
            "why": why,
            "dual_owners": dual,
            "resolved_owner": resolved_owner,
            "delivered": len(events),
            "accounting": cluster2.accounting(),
        }
        cluster2.shutdown_workers()
        cluster2.close()
        cluster.close()
        return out
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)
        shutil.rmtree(priv, ignore_errors=True)


# ------------------------------------------------- gateway HA matrix


def run_gateway_kill_point(
    point: str,
    *,
    at: int | None = None,
    workers: int = 2,
    sessions: int = 6,
    seed: int = 0,
    n_samples: int = 600,
    window: int = 100,
    hop: int = 50,
    # the gateway forwards synchronously, so its serve loop cannot
    # renew while a worker call is in flight: the lease must outlast
    # the longest forward stall (first-dispatch warmup on a cold
    # worker is ~0.5s) or the standby steals it mid-round — benign for
    # data (worker watermarks are the truth) but it would turn the
    # matrix's "kill" cells into accidental pre-kill flips
    lease_s: float = 1.0,
    handoff_round: int | None = None,
) -> dict:
    """One cell of the gateway-pair failover matrix: kill the ACTIVE
    gateway of an elected pair at one of its stage boundaries
    (``chaos.GATEWAY_KILL_POINTS``) while a reconnecting HA client is
    mid-delivery, or — with the pseudo-point ``"drain"`` — restart it
    GRACEFULLY instead (``shutdown {"drain": true}``: in-flight frames
    finish, refusals carry ``{"moved": ...}``, the lease is released
    early).  The acceptance bar is identical for both, which is the
    drain-indistinguishability pin: the standby takes the lease, the
    client resumes from the workers' watermarks, zero windows lost,
    and the scored stream BIT-IDENTICAL to an un-killed IN-PROCESS
    reference run of the same schedule.

    The gateway owns no session state (workers journal, the lease
    directory elects), so the kill never touches a journal — what this
    matrix proves is that the FRONT DOOR moving costs nothing: edge
    dedup-by-watermark absorbs the client's replayed frames and the
    fenced lease generation rejects any late ack from the deposed
    leader."""
    from har_tpu.serve.net.client import HAGatewayClient
    from har_tpu.serve.net.gateway import launch_gateway_pair
    from har_tpu.serve.net.ingest import IngestConfig
    from har_tpu.serve.net.rpc import RpcClient, RpcError
    from har_tpu.utils.backoff import BackoffPolicy

    drain = point == "drain"
    if not drain and point not in GATEWAY_KILL_POINTS:
        raise ValueError(f"unknown gateway kill point {point!r}")
    at = (_DEFAULT_AT.get(point, 1) if at is None else at)
    rounds = n_samples // hop
    if handoff_round is None:
        handoff_round = rounds // 3
    # the handoff cells need an explicit drain request to reach the
    # kill point (or to trigger the graceful restart)
    handoff = drain or point == "mid_lease_handoff"
    recordings = _recordings(sessions, n_samples, 3, seed)
    model = AnalyticDemoModel()

    def loader(ver):
        return model

    # ---- reference: the un-killed IN-PROCESS cluster run ------------
    ref_root = tempfile.mkdtemp(prefix="har_gwref_")
    try:
        ref_clock = FakeClock()
        ref = _build_cluster(
            ref_root, ref_clock, sessions=sessions, workers=workers,
            window=window, hop=hop, model=model,
            flush_every=512, snapshot_every=40, loader=loader,
        )
        for w in ref._workers.values():
            w.server._fault_hook = None
        for i in range(sessions):
            ref.add_session(i)
        ref_events: list = []
        for r in range(rounds):
            for i in range(sessions):
                ref.push(i, recordings[i][r * hop:(r + 1) * hop])
            ref_events.extend(ref.poll(force=True))
            ref_clock.advance(0.01)
        ref_events.extend(ref.flush())
        ref.close()
    finally:
        shutil.rmtree(ref_root, ignore_errors=True)

    # ---- the wire run: worker fleet + elected gateway pair ----------
    root = tempfile.mkdtemp(prefix="har_gwchaos_")
    procs: list = []
    client = None
    try:
        net_workers = launch_workers(
            root, workers, window=window, hop=hop, target_batch=32,
            max_delay_ms=0.0, retries=1, flush_every=512,
            snapshot_every=40,
        )
        procs.extend(w.process for w in net_workers)
        pair = launch_gateway_pair(
            root, net_workers, deadline_s=2.0, config=IngestConfig(),
            lease_s=lease_s,
            chaos_point=None if drain else point,
            chaos_at=at,
        )
        procs.extend(p for p, _, _ in pair)
        (proc_a, host_a, port_a), (_, host_b, port_b) = pair
        client = HAGatewayClient(
            [f"{host_a}:{port_a}", f"{host_b}:{port_b}"],
            deadline_s=2.0, retries=1, seed=seed,
            reconnect=BackoffPolicy(
                base_ms=20.0, cap_ms=250.0, factor=2.0, jitter=0.25
            ),
        )
        for i in range(sessions):
            client.add_session(i)
        events: list = []
        for r in range(rounds):
            if handoff and r == handoff_round:
                # address gateway A DIRECTLY, not through the HA
                # client: a deadline-retried drain that followed the
                # lease would drain the NEW leader too and leave the
                # pair dry
                probe = RpcClient(
                    host_a, port_a, deadline_s=1.0, retries=0
                )
                try:
                    probe.call("shutdown", {"drain": True})
                except RpcError:
                    pass  # mid_lease_handoff kills A inside the call
                finally:
                    probe.close()
            for i in range(sessions):
                client.push(i, recordings[i][r * hop:(r + 1) * hop])
            events.extend(client.poll(force=True))
        events.extend(client.flush())
        acct = client.accounting()
        gw_stats = client.gateway_stats()

        # ---- fired check -------------------------------------------
        deadline = time.monotonic() + 5.0
        while proc_a.poll() is None and time.monotonic() < deadline:
            time.sleep(0.02)
        rc_a = proc_a.poll()
        why = None
        if drain:
            if rc_a != 0:
                why = (
                    f"drain: gateway A exited {rc_a!r}, wanted a clean "
                    "0 after the grace window"
                )
        elif rc_a is None:
            why = f"{point}: the chaos plan never fired (A still alive)"

        # ---- verdict: the same bar as every other matrix ------------
        def _per_sid(evts):
            out: dict = {}
            for fe in evts:
                out.setdefault(fe.session_id, []).append(
                    _event_fields(fe)
                )
            return out

        ref_by = _per_sid(ref_events)
        got_by = _per_sid(events)
        keys = [(fe.session_id, fe.event.t_index) for fe in events]
        if why is None and len(keys) != len(set(keys)):
            why = (
                "duplicate (session, t_index) events — the replayed "
                "frame was double-ingested across the lease flip"
            )
        windows_lost = len(ref_events) - len(events)
        if why is None and windows_lost != 0:
            why = f"{windows_lost} windows lost across the lease flip"
        if why is None and got_by != ref_by:
            why = (
                "scored stream not bit-identical to the un-killed "
                "in-process reference"
            )
        if why is None and not acct.get("balanced", False):
            why = f"conservation violated after failover: {acct!r}"
        if why is None and int(acct.get("lost_in_crash", 0)) != 0:
            why = (
                f"{acct['lost_in_crash']} windows declared lost — the "
                "gateway kill must not cost journal suffix"
            )
        if why is None and client.gen < 2:
            why = (
                "client never saw a fenced generation bump "
                f"(gen={client.gen}) — did the lease actually move?"
            )
        if why is None and client.failover_episodes < 1:
            why = "client recorded no failover episode"
        out = {
            "ok": why is None,
            "point": point,
            "why": why,
            "drain": drain,
            "windows_lost": windows_lost,
            "delivered": len(events),
            "failover_ms": float(client.last_failover_ms or 0.0),
            "reconnects": client.reconnects,
            "moved_receipts": client.moved_receipts,
            "stale_acks_rejected": client.stale_acks_rejected,
            "resumed_sessions": len(client.resumed),
            "deduped_samples": client.deduped_samples,
            "gateways": 2,
            "gateway_exit": rc_a,
            "lease_gen": client.gen,
            "standby_lease_wins": int(gw_stats.get("lease_wins", 0)),
            "accounting": acct,
        }
        client.shutdown()
        return out
    finally:
        if client is not None:
            client.close()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)
