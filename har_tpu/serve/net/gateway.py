"""``har serve-gateway`` — the fleet's wire-rate ingest front door.

Clients do not talk to workers.  They talk to ONE gateway process
speaking the same journal-frame wire protocol the workers do, and the
gateway multiplexes them onto the fleet:

  - a client buffers its per-session ``push`` calls and ships each
    delivery round as ONE batched push frame (``wire.encode_chunk_batch``
    — one frame carrying every session's chunk for the round, in
    delivery order), collapsing a round's N push RPCs into one;

  - admission control and the shed ladder run AT THE EDGE, before the
    frame's payload is even assembled: the RpcServer's admission hook
    judges each push frame from its header alone (session count,
    declared byte length, staleness watermark — ``ingest.EdgeAdmission``)
    and a refused frame is answered ``{"shed": reason}`` without a
    payload decode, a numpy array, or a worker RPC.  Refusals are
    DECLARED — the client counts them against its own cursors, so the
    conservation law extends to the edge: every sample a client sends
    is refused-with-a-receipt or lands in fleet accounting;

  - admitted frames decode to zero-copy views over the received
    payload (``wire.decode_chunk_batch``) and route through
    ``FleetCluster.push_many`` — grouped per owning worker, one batched
    RPC per worker, landing in each engine's reserved ``StagingArena``
    slots in delivery order.

The gateway is a FRONT DOOR, not a second control plane: it owns no
placement, no membership, no journal.  Failover, leases and the ledger
stay in the NetCluster it fronts; the gateway's only state is the
admission ladder's backlog estimate, resynced from fleet accounting.

Engine-free at import: the heavy imports (engine, cluster controller)
happen inside ``main``/handlers, so the admission path stays cheap to
import and the module is testable without a jax backend behind it.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

from har_tpu.serve.net import wire
from har_tpu.serve.net.ingest import EdgeAdmission, IngestConfig
from har_tpu.serve.net.rpc import RpcClient, RpcServer


class IngestGateway:
    """One RpcServer fronting a cluster (in-process ``FleetCluster`` or
    a ``NetCluster`` of worker processes — the gateway is transport-
    blind, same seam as the controller itself).

    The admission hook only judges ``push_many`` frames; the control
    surface (add_session, poll, accounting, ...) is never shed — a
    client that cannot deliver data can still drain events and settle.
    """

    def __init__(
        self,
        cluster,
        *,
        config: IngestConfig | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.cluster = cluster
        self.admission = EdgeAdmission(config)
        self.rounds = 0
        self._shutdown = False
        self.rpc = RpcServer(
            self._handlers(),
            host=host,
            port=port,
            admission=self._admit,
        )

    # ------------------------------------------------------- admission

    def _admit(self, meta: dict, payload_len: int) -> str | None:
        # only data-plane push frames face the ladder: shedding a poll
        # would wedge the very drain that lowers the backlog
        if meta.get("m") != "push_many":
            return None
        return self.admission.admit(meta, payload_len)

    # ------------------------------------------------------- handlers

    def _handlers(self) -> dict:
        cluster = self.cluster
        adm = self.admission

        def ok(meta=None, payload=b""):
            return dict(meta or {}), payload

        def heartbeat(meta, payload):
            return ok()

        def geometry(meta, payload):
            # the one datum a front-door client needs to chunk its
            # stream: the fleet's hop (frames are sliced client-side)
            return ok({"hop": int(cluster.hop)})

        def add_session(meta, payload):
            from har_tpu.serve.journal import monitor_from_state

            cluster.add_session(
                meta["sid"],
                monitor=monitor_from_state(meta.get("mon")),
            )
            return ok()

        def push_many(meta, payload):
            # the admission hook already said yes (header-only); the
            # decode below yields zero-copy views over the payload and
            # the cluster routes them per owning worker in delivery
            # order
            items = wire.decode_chunk_batch(meta, payload)
            n = cluster.push_many(
                [sid for sid, _ in items], [c for _, c in items]
            )
            adm.note_enqueued(n)
            self.rounds += 1
            return ok({"r": int(n)})

        def poll(meta, payload):
            events = cluster.poll(force=bool(meta.get("force")))
            adm.note_retired(len(events))
            return wire.encode_events(events)

        def disconnect(meta, payload):
            events = cluster.disconnect_sessions(meta["sids"])
            adm.note_retired(len(events))
            return wire.encode_events(events)

        def flush(meta, payload):
            events = cluster.flush()
            adm.note_retired(len(events))
            return wire.encode_events(events)

        def watermark(meta, payload):
            return ok({"r": int(cluster.watermark(meta["sid"]))})

        def accounting(meta, payload):
            acct = cluster.accounting()
            # engine-side declared sheds retire windows the gateway
            # never sees come back as events — pin the ladder's backlog
            # estimate to the fleet's true pending count
            adm.resync_backlog(acct.get("pending", 0))
            return ok({"r": acct})

        def gateway_stats(meta, payload):
            return ok({"r": {**adm.snapshot(), "rounds": self.rounds}})

        def shutdown(meta, payload):
            self._shutdown = True
            return ok()

        return {
            "heartbeat": heartbeat,
            "geometry": geometry,
            "add_session": add_session,
            "push_many": push_many,
            "poll": poll,
            "disconnect": disconnect,
            "flush": flush,
            "watermark": watermark,
            "accounting": accounting,
            "gateway_stats": gateway_stats,
            "shutdown": shutdown,
        }

    # ----------------------------------------------------------- loop

    def serve_forever(self, *, max_idle_s: float = 0.0) -> int:
        try:
            while not self._shutdown:
                self.rpc.step(0.05)
                if (
                    max_idle_s
                    and time.monotonic() - self.rpc.last_activity
                    > max_idle_s
                ):
                    return 2  # orphaned: the client side went away
            return 0
        finally:
            self.close()

    def close(self) -> None:
        # the cluster (and its worker processes) belong to whoever
        # built them; the gateway only closes its own listener
        self.rpc.close()


class GatewayClient:
    """The front-door client — ``drive_trace``-compatible, so every
    traffic harness that drives an engine or a cluster in-process
    drives the gateway over real sockets unchanged.

    ``push`` BUFFERS (returns 0); the round's buffered chunks leave as
    one batched push frame at the next ``poll``/``flush``/``disconnect``
    — the same before-the-poll delivery point the in-process loop has,
    so per-session arrival order (and therefore every scored event) is
    bit-identical to an in-process run.  The frame's header carries the
    client's sample watermark; a ``{"shed": reason}`` answer is counted
    against the client's own cursors (``edge_sheds`` / ``shed_samples``
    / ``shed_by_reason``) — the declared-refusal receipt the
    conservation law at the edge is pinned on.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        deadline_s: float = 10.0,
        retries: int = 2,
    ):
        self._client = RpcClient(
            host, port, deadline_s=deadline_s, retries=retries
        )
        resp, _ = self._client.call("geometry")
        self.hop = int(resp["hop"])
        self._pending: list = []  # [(sid, float32 chunk)] this round
        self._wm = 0  # samples pushed so far: the frame watermark
        self.windows_enqueued = 0
        self.frames_sent = 0
        self.edge_sheds = 0
        self.shed_sessions = 0
        self.shed_samples = 0
        self.shed_by_reason: dict[str, int] = {}

    # -------------------------------------------------- the data plane

    def add_session(self, session_id, *, monitor=None) -> None:
        from har_tpu.serve.journal import monitor_state

        self._client.call(
            "add_session",
            {"sid": session_id, "mon": monitor_state(monitor)},
        )

    def push(self, session_id, samples) -> int:
        """Buffer one session's chunk for this round's batched frame.
        Returns 0 — enqueue receipts arrive with the frame's response
        (``windows_enqueued``); a drive-loop that sums push returns
        reads the true count from gateway accounting instead."""
        arr = np.ascontiguousarray(samples, np.float32)
        self._pending.append((session_id, arr))
        self._wm += int(arr.shape[0])
        return 0

    def _flush_pending(self) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        meta, payload = wire.encode_chunk_batch(batch)
        meta["wm"] = self._wm
        resp, _ = self._client.call("push_many", meta, payload)
        self.frames_sent += 1
        if "shed" in resp:
            reason = resp["shed"]
            self.edge_sheds += 1
            self.shed_sessions += len(batch)
            self.shed_samples += sum(
                int(a.shape[0]) for _, a in batch
            )
            self.shed_by_reason[reason] = (
                self.shed_by_reason.get(reason, 0) + 1
            )
        else:
            self.windows_enqueued += int(resp["r"])

    def poll(self, *, force: bool = False) -> list:
        self._flush_pending()
        resp, payload = self._client.call("poll", {"force": bool(force)})
        return wire.decode_events(resp, payload)

    def disconnect_sessions(self, session_ids) -> list:
        self._flush_pending()
        resp, payload = self._client.call(
            "disconnect", {"sids": list(session_ids)}
        )
        return wire.decode_events(resp, payload)

    def flush(self) -> list:
        self._flush_pending()
        resp, payload = self._client.call("flush")
        return wire.decode_events(resp, payload)

    def watermark(self, session_id) -> int:
        resp, _ = self._client.call("watermark", {"sid": session_id})
        return int(resp["r"])

    # ----------------------------------------------------- observation

    def accounting(self) -> dict:
        resp, _ = self._client.call("accounting")
        return resp["r"]

    def gateway_stats(self) -> dict:
        resp, _ = self._client.call("gateway_stats")
        return resp["r"]

    # ------------------------------------------------------- lifecycle

    def shutdown(self) -> None:
        try:
            self._client.call("shutdown")
        except Exception:
            pass

    def close(self) -> None:
        self._client.close()


# --------------------------------------------------------- entrypoint


def build_parser() -> argparse.ArgumentParser:
    dflt = IngestConfig()
    ap = argparse.ArgumentParser(
        prog="har serve-gateway",
        description=(
            "the fleet's ingest front door (har_tpu.serve.net.gateway) "
            "— one process speaking the journal-frame wire protocol to "
            "clients, multiplexing batched push frames onto already-"
            "running `har serve-worker` processes with header-only edge "
            "admission; prints one JSON ready line {host, port, pid}"
        ),
    )
    ap.add_argument("--root", required=True,
                    help="cluster root directory (failover staging)")
    ap.add_argument("--workers-json", required=True,
                    help="JSON list of running workers: "
                         '[{"id", "host", "port", "journal"}, ...]')
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral; the ready line reports it")
    ap.add_argument("--model", default="demo")
    ap.add_argument("--deadline-s", type=float, default=2.0,
                    help="per-RPC deadline toward the workers")
    ap.add_argument("--soft-backlog", type=int, default=dflt.soft_backlog)
    ap.add_argument("--hard-backlog", type=int, default=dflt.hard_backlog)
    ap.add_argument("--max-frame-sessions", type=int,
                    default=dflt.max_frame_sessions)
    ap.add_argument("--max-frame-bytes", type=int,
                    default=dflt.max_frame_bytes)
    ap.add_argument("--max-watermark-lag", type=int,
                    default=dflt.max_watermark_lag)
    ap.add_argument("--max-idle-s", type=float, default=120.0,
                    help="exit when no RPC arrives for this long "
                         "(orphan protection); 0 disables")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from har_tpu.serve.net.client import NetWorker
    from har_tpu.serve.net.controller import NetCluster
    from har_tpu.serve.net.worker import model_pool

    models = model_pool(args.model)
    net_workers = [
        NetWorker(
            spec["id"],
            spec["host"],
            int(spec["port"]),
            spec["journal"],
            deadline_s=args.deadline_s,
        )
        for spec in json.loads(args.workers_json)
    ]
    # the fleet's geometry is the workers' geometry — ask one instead
    # of trusting a default: the client slices its stream by the hop
    # the gateway advertises, and a mismatch would silently starve (or
    # flood) every window assembler behind the front door
    geo = net_workers[0].geometry()
    cluster = NetCluster(
        models["A"],
        args.root,
        window=int(geo["window"]),
        hop=int(geo["hop"]),
        channels=int(geo["channels"]),
        smoothing=geo["smoothing"],
        loader=lambda ver: models.get(ver, models["A"]),
        _workers=net_workers,
    )
    gw = IngestGateway(
        cluster,
        config=IngestConfig(
            soft_backlog=args.soft_backlog,
            hard_backlog=args.hard_backlog,
            max_frame_sessions=args.max_frame_sessions,
            max_frame_bytes=args.max_frame_bytes,
            max_watermark_lag=args.max_watermark_lag,
        ),
        host=args.host,
        port=args.port,
    )
    print(
        json.dumps(
            {"host": gw.rpc.host, "port": gw.rpc.port, "pid": os.getpid()}
        ),
        flush=True,
    )
    try:
        return gw.serve_forever(max_idle_s=args.max_idle_s)
    finally:
        for w in net_workers:
            w.close()


def launch_gateway(
    root: str,
    workers,
    *,
    model: str = "demo",
    host: str = "127.0.0.1",
    deadline_s: float = 2.0,
    config: IngestConfig | None = None,
    max_idle_s: float = 120.0,
    ready_timeout_s: float = 30.0,
):
    """Spawn one ``har serve-gateway`` subprocess fronting already-
    running workers (``NetWorker`` proxies from ``launch_workers``) and
    return ``(proc, host, port)`` once its ready line lands.  Stderr is
    captured to ``<root>/gateway.stderr.log`` for post-mortems."""
    from har_tpu.serve.net.controller import _read_ready_line

    cfg = config or IngestConfig()
    specs = [
        {
            "id": w.worker_id,
            "host": w.host,
            "port": w.port,
            "journal": w.journal_dir,
        }
        for w in workers
    ]
    os.makedirs(root, exist_ok=True)
    cmd = [
        sys.executable, "-m", "har_tpu.serve.net.gateway",
        "--root", root,
        "--workers-json", json.dumps(specs),
        "--host", host,
        "--model", model,
        "--deadline-s", str(deadline_s),
        "--soft-backlog", str(cfg.soft_backlog),
        "--hard-backlog", str(cfg.hard_backlog),
        "--max-frame-sessions", str(cfg.max_frame_sessions),
        "--max-frame-bytes", str(cfg.max_frame_bytes),
        "--max-watermark-lag", str(cfg.max_watermark_lag),
        "--max-idle-s", str(max_idle_s),
    ]
    err = open(os.path.join(root, "gateway.stderr.log"), "wb")
    try:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=err, text=True
        )
    finally:
        err.close()
    try:
        ready = _read_ready_line(
            proc, "gateway", root, ready_timeout_s,
            log_name="gateway.stderr.log",
        )
    except BaseException:
        try:
            proc.kill()
        except OSError:
            pass
        raise
    return proc, ready["host"], ready["port"]


if __name__ == "__main__":  # pragma: no cover - subprocess entrypoint
    sys.exit(main(sys.argv[1:]))
