"""``har serve-gateway`` — the fleet's wire-rate ingest front door.

Clients do not talk to workers.  They talk to a GATEWAY speaking the
same journal-frame wire protocol the workers do, and the gateway
multiplexes them onto the fleet:

  - a client buffers its per-session ``push`` calls and ships each
    delivery round as ONE batched push frame (``wire.encode_chunk_batch``
    — one frame carrying every session's chunk for the round, in
    delivery order), collapsing a round's N push RPCs into one;

  - admission control and the shed ladder run AT THE EDGE, before the
    frame's payload is even assembled: the RpcServer's admission hook
    judges each push frame from its header alone (session count,
    declared byte length, staleness watermark, tenant identity —
    ``ingest.EdgeAdmission``) and a refused frame is answered
    ``{"shed": reason}`` without a payload decode, a numpy array, or a
    worker RPC.  Refusals are DECLARED — the client counts them against
    its own cursors, so the conservation law extends to the edge: every
    sample a client sends is refused-with-a-receipt or lands in fleet
    accounting;

  - admitted frames decode to zero-copy views over the received
    payload (``wire.decode_chunk_batch``) and route through
    ``FleetCluster.push_many`` — grouped per owning worker, one batched
    RPC per worker, landing in each engine's reserved ``StagingArena``
    slots in delivery order.

HIGH AVAILABILITY is a pair of gateways behind the controller
replicas' lease election (``election.LeaderLease`` on a shared
``ha_root``).  The gateway owns no durable state — no placement, no
membership, no journal — so failover is JUST THE LEASE MOVING:

  - the leader's id IS its dialable ``host:port``, so the lease file
    doubles as the leader directory: a standby answers every data-plane
    frame with a declared ``{"moved": leader_addr}`` receipt (header-
    only, payload skipped — never a silent hangup), and the client
    redials the address in the receipt;
  - every leader response is stamped with the fenced lease generation
    (``gen``); a deposed leader's late ack carries a smaller generation
    than the client has already seen and is REJECTED client-side, then
    re-delivered to the real leader — where dedup-by-watermark makes
    the re-send idempotent instead of double-counted;
  - the winner rebuilds its fleet attachment from actual worker
    ownership (``NetCluster.takeover`` — derived, never trusted across
    generations) and seeds its per-session delivery offsets lazily from
    the workers' ``watermark(sid)``: re-sent chunk rows below the
    watermark are trimmed at the edge (``dd`` in the push receipt), so
    a client's post-reconnect replay lands exactly once and the scored
    event stream stays bit-identical to an unbroken run;
  - a graceful drain (``shutdown {"drain": true}``) finishes in-flight
    frames, answers ``{"moved": ...}`` for new ones, and RELEASES the
    lease early (``LeaderLease.release``) — a planned restart flips the
    pair as fast as a crash failover, minus the detection wait.

Engine-free at import: the heavy imports (engine, cluster controller,
election's controller config) happen inside ``main``/handlers, so the
admission path stays cheap to import and the module is testable
without a jax backend behind it.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

from har_tpu.serve.net import wire
from har_tpu.serve.net.ingest import EdgeAdmission, IngestConfig
from har_tpu.serve.net.rpc import RpcClient, RpcError, RpcServer


class IngestGateway:
    """One RpcServer fronting a cluster (in-process ``FleetCluster`` or
    a ``NetCluster`` of worker processes — the gateway is transport-
    blind, same seam as the controller itself).

    The admission hook only judges ``push_many`` frames; the control
    surface (add_session, poll, accounting, ...) is never shed — a
    client that cannot deliver data can still drain events and settle.

    Two attachment modes:

      - ``cluster`` (an object): single-gateway mode, the PR-16 shape —
        always leading, no lease;
      - ``cluster_factory`` (+ ``ha_root``): HA-pair mode — the cluster
        attachment is built ON WINNING THE LEASE (the factory runs
        ``NetCluster.takeover``, deriving placement from actual worker
        ownership) and dropped on resigning, so a deposed gateway holds
        no stale attachment and the winner trusts nothing across
        generations.
    """

    def __init__(
        self,
        cluster=None,
        *,
        cluster_factory=None,
        config: IngestConfig | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        ha_root: str | None = None,
        lease_s: float = 1.0,
        drain_grace_s: float = 0.25,
        wall=None,
        chaos=None,
        stats=None,
    ):
        if cluster is None and cluster_factory is None:
            raise ValueError("need a cluster or a cluster_factory")
        self.cluster = cluster
        self._cluster_factory = cluster_factory
        self.admission = EdgeAdmission(config, stats=stats)
        self.rounds = 0
        self.deduped_samples = 0
        self.lease_wins = 0
        self.lease_s = float(lease_s)
        self.drain_grace_s = float(drain_grace_s)
        self.chaos = chaos
        self._shutdown = False
        self._draining = False
        self._drain_deadline = 0.0
        # per-session delivery offsets (dedup-by-watermark): the end of
        # the last admitted chunk per sid, lazily seeded from the
        # workers' watermark(sid) — cleared on every lease win so a new
        # leader re-derives instead of trusting its own stale view
        self._session_off: dict = {}
        # sid -> tenant id (from add_session), so retired events drain
        # the RIGHT tenant's backlog slice
        self._session_tenant: dict = {}
        self.rpc = RpcServer(
            self._handlers(),
            host=host,
            port=port,
            admission=self._admit,
        )
        # the leader id IS the dialable address — the lease file is
        # thereby also the leader DIRECTORY the moved receipts quote
        self.gateway_id = f"{self.rpc.host}:{self.rpc.port}"
        self.lease = None
        if ha_root is not None:
            from har_tpu.serve.net.election import LeaderLease

            self.lease = LeaderLease(ha_root, lease_s=lease_s, wall=wall)
        self._leading = self.lease is None
        self.generation = 0

    # ----------------------------------------------------------- chaos

    def _chaos(self, point: str) -> None:
        if self.chaos is not None:
            self.chaos(point)

    # ----------------------------------------------------------- lease

    def _leader_addr(self) -> str | None:
        if self.lease is None:
            return None
        return self.lease.holder()

    def step_lease(self) -> str:
        """One lease duty cycle (paced by ``serve_forever``): leader
        renews (resigning on refusal — a larger generation exists and
        fencing forbids serving under a stale mandate), standby
        campaigns, and a winner rebuilds its fleet attachment before it
        serves.  Returns the role after the step."""
        if self.lease is None:
            return "leader"
        if self._leading:
            if not self.lease.renew(self.gateway_id, self.generation):
                self._resign()
                return "standby"
            if self.cluster is None and self._cluster_factory is not None:
                return self._try_attach()
            return "leader"
        gen = self.lease.campaign(self.gateway_id)
        if gen is None:
            return "standby"
        self.generation = gen
        self.lease_wins += 1
        self._leading = True
        self._session_off.clear()
        if self._cluster_factory is not None:
            if self.cluster is not None:
                self._detach_cluster()
            return self._try_attach()
        return "leader"

    def _try_attach(self) -> str:
        """Build the fleet attachment under the held lease; a transient
        failure (slow worker, I/O) keeps the lease and retries next
        step — same mandate-retry stance as ``ControllerReplica``."""
        try:
            self.cluster = self._cluster_factory()
        except Exception:
            return "campaigning"
        return "leader"

    def _resign(self) -> None:
        self._leading = False
        if self._cluster_factory is not None and self.cluster is not None:
            self._detach_cluster()

    def _detach_cluster(self) -> None:
        # fence only this gateway's worker CLIENTS — the worker
        # processes (and their journals) belong to the fleet
        try:
            for w in self.cluster._workers.values():
                w.close()
        except Exception:
            pass
        self.cluster = None

    def _begin_drain(self) -> None:
        """Graceful hand-off: in-flight (already admitted) frames
        finish, new pushes get ``{"moved": ...}``, and the lease is
        released EARLY so the peer's campaign wins immediately — a
        planned restart indistinguishable from a fast failover."""
        if self._draining:
            return
        self._draining = True
        self._drain_deadline = time.monotonic() + self.drain_grace_s
        self._chaos("mid_lease_handoff")
        if self.lease is not None and self._leading:
            self.lease.release(self.gateway_id, self.generation)

    # ------------------------------------------------------- admission

    def _admit(self, meta: dict, payload_len: int):
        # only data-plane push frames face the ladder: shedding a poll
        # would wedge the very drain that lowers the backlog
        if meta.get("m") != "push_many":
            return None
        self._chaos("mid_frame_recv")
        if not self._leading or self._draining or self.cluster is None:
            # the standby's declared refusal: never a silent hangup —
            # the receipt carries the leader's address for the redial
            return {"moved": self._leader_addr()}
        return self.admission.admit(meta, payload_len)

    # ------------------------------------------------------- handlers

    def _retire(self, events) -> None:
        """Drain the backlog estimate, attributed to each event's
        session tenant (events from sessions added without a tenant id
        land on the default slice)."""
        adm = self.admission
        if not self._session_tenant:
            adm.note_retired(len(events))
            return
        counts: dict = {}
        for fe in events:
            t = self._session_tenant.get(fe.session_id)
            counts[t] = counts.get(t, 0) + 1
        for t, n in counts.items():
            adm.note_retired(n, t)

    def _guarded(self, fn):
        """Data/control-plane handler wrapper: a non-leader answers the
        declared ``{"moved": leader_addr}`` receipt, a leader stamps its
        fenced lease generation on every response — the gen a client
        uses to reject a deposed leader's late acks."""

        def wrapped(meta, payload):
            if not self._leading or self.cluster is None:
                return {"moved": self._leader_addr()}, b""
            m, p = fn(meta, payload)
            if self.lease is not None:
                m["gen"] = int(self.generation)
            return m, p

        return wrapped

    def _handlers(self) -> dict:
        adm = self.admission

        def ok(meta=None, payload=b""):
            return dict(meta or {}), payload

        def heartbeat(meta, payload):
            return ok()

        def geometry(meta, payload):
            # the one datum a front-door client needs to chunk its
            # stream: the fleet's hop (frames are sliced client-side)
            return ok({"hop": int(self.cluster.hop)})

        def add_session(meta, payload):
            from har_tpu.serve.journal import monitor_from_state

            self.cluster.add_session(
                meta["sid"],
                monitor=monitor_from_state(meta.get("mon")),
            )
            if meta.get("tn") is not None:
                self._session_tenant[meta["sid"]] = str(meta["tn"])
            return ok()

        def push_many(meta, payload):
            # the admission hook already said yes (header-only); the
            # decode below yields zero-copy views over the payload and
            # the cluster routes them per owning worker in delivery
            # order.  Chunks stamped with a stream offset (``o``) are
            # deduplicated against the session's delivery watermark:
            # rows below it were already delivered (a post-reconnect
            # replay) and are trimmed, idempotently, with a ``dd``
            # receipt — never double-staged.
            tenant = adm.resolve_tenant(meta)
            items = wire.decode_chunk_batch(meta, payload)
            entries = meta.get("chunks") or []
            sids, chunks, deduped = [], [], 0
            for em, (sid, arr) in zip(entries, items):
                # re-learn sid -> tenant from the frame itself: a fresh
                # leader never saw the client's add_session, and retire
                # attribution must follow the session to the new slice
                if tenant is not None:
                    self._session_tenant[sid] = tenant
                off = em.get("o")
                if off is not None:
                    base = self._session_off.get(sid)
                    if base is None:
                        # lazy watermark seed: what the WORKERS durably
                        # saw — the only delivery truth that survives a
                        # gateway failover
                        base = int(self.cluster.watermark(sid))
                    off_i = int(off)
                    n_orig = int(arr.shape[0])
                    skip = base - off_i
                    if skip > 0:
                        k = min(skip, n_orig)
                        deduped += k
                        arr = arr[k:]
                    self._session_off[sid] = max(base, off_i + n_orig)
                if int(arr.shape[0]):
                    sids.append(sid)
                    chunks.append(arr)
            self._chaos("post_accept_pre_forward")
            n = self.cluster.push_many(sids, chunks) if sids else 0
            adm.note_enqueued(n, tenant)
            self.rounds += 1
            self.deduped_samples += deduped
            return ok({"r": int(n), "dd": int(deduped)})

        def poll(meta, payload):
            events = self.cluster.poll(force=bool(meta.get("force")))
            self._retire(events)
            return wire.encode_events(events)

        def disconnect(meta, payload):
            events = self.cluster.disconnect_sessions(meta["sids"])
            self._retire(events)
            for sid in meta["sids"]:
                # a later re-add restarts the session's stream at 0 —
                # a stale offset base would wrongly trim its first rows
                self._session_off.pop(sid, None)
            return wire.encode_events(events)

        def flush(meta, payload):
            events = self.cluster.flush()
            self._retire(events)
            return wire.encode_events(events)

        def watermark(meta, payload):
            return ok({"r": int(self.cluster.watermark(meta["sid"]))})

        def accounting(meta, payload):
            acct = self.cluster.accounting()
            # engine-side declared sheds retire windows the gateway
            # never sees come back as events — pin the ladder's backlog
            # estimate to the fleet's true pending count
            adm.resync_backlog(acct.get("pending", 0))
            return ok({"r": acct})

        def gateway_stats(meta, payload):
            return ok(
                {
                    "r": {
                        **adm.snapshot(),
                        "rounds": self.rounds,
                        "deduped_samples": self.deduped_samples,
                        "lease_wins": self.lease_wins,
                        "gen": int(self.generation),
                    }
                }
            )

        def whois(meta, payload):
            # unguarded on purpose: the one question a standby must
            # answer in its own voice
            role = (
                "draining"
                if self._draining
                else "leader"
                if self._leading
                else "standby"
            )
            return ok(
                {
                    "role": role,
                    "leader": self._leader_addr(),
                    "gen": int(self.generation),
                }
            )

        def shutdown(meta, payload):
            if meta.get("drain"):
                self._begin_drain()
            else:
                self._shutdown = True
            return ok()

        return {
            "heartbeat": heartbeat,
            "geometry": self._guarded(geometry),
            "add_session": self._guarded(add_session),
            "push_many": self._guarded(push_many),
            "poll": self._guarded(poll),
            "disconnect": self._guarded(disconnect),
            "flush": self._guarded(flush),
            "watermark": self._guarded(watermark),
            "accounting": self._guarded(accounting),
            "gateway_stats": gateway_stats,
            "whois": whois,
            "shutdown": shutdown,
        }

    # ----------------------------------------------------------- loop

    def serve_forever(self, *, max_idle_s: float = 0.0) -> int:
        next_lease = 0.0
        try:
            while not self._shutdown:
                self.rpc.step(0.05)
                now = time.monotonic()
                if (
                    self.lease is not None
                    and not self._draining
                    and now >= next_lease
                ):
                    self.step_lease()
                    # renew/campaign well inside the lease term
                    next_lease = now + self.lease_s * 0.3
                if self._draining and now >= self._drain_deadline:
                    return 0
                if max_idle_s:
                    # orphan protection; the standby receives no client
                    # traffic BY DESIGN, so its window is 4x the
                    # leader's — long enough to outlive a slow leader,
                    # short enough not to outlive a dead suite
                    window = (
                        max_idle_s
                        if (self.lease is None or self._leading)
                        else 4.0 * max_idle_s
                    )
                    if now - self.rpc.last_activity > window:
                        return 2
            return 0
        finally:
            self.close()

    def close(self) -> None:
        # the cluster (and its worker processes) belong to whoever
        # built them; a factory-built attachment is this gateway's own
        # and its worker SOCKETS close with it
        if self._cluster_factory is not None and self.cluster is not None:
            self._detach_cluster()
        self.rpc.close()


class GatewayClient:
    """The front-door client — ``drive_trace``-compatible, so every
    traffic harness that drives an engine or a cluster in-process
    drives the gateway over real sockets unchanged.

    ``push`` BUFFERS (returns 0); the round's buffered chunks leave as
    one batched push frame at the next ``poll``/``flush``/``disconnect``
    — the same before-the-poll delivery point the in-process loop has,
    so per-session arrival order (and therefore every scored event) is
    bit-identical to an in-process run.  The frame's header carries the
    client's sample watermark and tenant id; each chunk carries its
    session-stream OFFSET (the delivery-coordinate position of its
    first row) so the gateway can trim already-delivered rows after a
    reconnect replay.  Offsets count DELIVERED samples only: a
    ``{"shed": reason}`` answer rolls the batch's offsets back (shed
    samples never occupied delivery positions), keeping client offsets
    and worker watermarks in the same coordinate system.  Sheds are
    counted against the client's own cursors (``edge_sheds`` /
    ``shed_samples`` / ``shed_by_reason``) — the declared-refusal
    receipt the conservation law at the edge is pinned on.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        deadline_s: float = 10.0,
        retries: int = 2,
        tenant: str | None = None,
    ):
        self.tenant = tenant
        self._deadline_s = float(deadline_s)
        self._retries = int(retries)
        self._client = None
        self._dial(host, port)
        self._pending: list = []  # [(sid, float32 chunk, offset)]
        self._off: dict = {}  # sid -> delivered-sample offset
        self._wm = 0  # samples pushed so far: the frame watermark
        self.windows_enqueued = 0
        self.frames_sent = 0
        self.edge_sheds = 0
        self.shed_sessions = 0
        self.shed_samples = 0
        self.shed_by_reason: dict[str, int] = {}
        self.deduped_samples = 0
        resp, _ = self._call("geometry")
        self.hop = int(resp["hop"])

    # ------------------------------------------------------- transport

    def _dial(self, host: str, port: int) -> None:
        if self._client is not None:
            self._client.close()
        self._client = RpcClient(
            host, port, deadline_s=self._deadline_s, retries=self._retries
        )

    def _call(self, method: str, meta: dict | None = None,
              payload: bytes = b""):
        """One RPC through the pooled connection — the HA subclass
        overrides this seam with redial-and-resume."""
        return self._client.call(method, meta, payload)

    # -------------------------------------------------- the data plane

    def add_session(self, session_id, *, monitor=None) -> None:
        from har_tpu.serve.journal import monitor_state

        meta = {"sid": session_id, "mon": monitor_state(monitor)}
        if self.tenant is not None:
            meta["tn"] = self.tenant
        self._call("add_session", meta)

    def push(self, session_id, samples) -> int:
        """Buffer one session's chunk for this round's batched frame.
        Returns 0 — enqueue receipts arrive with the frame's response
        (``windows_enqueued``); a drive-loop that sums push returns
        reads the true count from gateway accounting instead."""
        arr = np.ascontiguousarray(samples, np.float32)
        off = self._off.get(session_id, 0)
        self._pending.append((session_id, arr, off))
        self._off[session_id] = off + int(arr.shape[0])
        self._wm += int(arr.shape[0])
        return 0

    def _flush_pending(self) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        meta, payload = wire.encode_chunk_batch(
            [(sid, arr) for sid, arr, _ in batch],
            offsets=[off for _, _, off in batch],
        )
        meta["wm"] = self._wm
        if self.tenant is not None:
            meta["tn"] = self.tenant
        resp, _ = self._call("push_many", meta, payload)
        self.frames_sent += 1
        if "shed" in resp:
            reason = resp["shed"]
            self.edge_sheds += 1
            self.shed_sessions += len(batch)
            self.shed_samples += sum(
                int(a.shape[0]) for _, a, _ in batch
            )
            self.shed_by_reason[reason] = (
                self.shed_by_reason.get(reason, 0) + 1
            )
            # shed samples never occupied delivery positions: roll the
            # offsets back so the stream's NEXT samples take them —
            # client offsets stay aligned with worker watermarks
            for sid, _, off in batch:
                if off < self._off.get(sid, 0):
                    self._off[sid] = off
        else:
            self.windows_enqueued += int(resp["r"])
            self.deduped_samples += int(resp.get("dd", 0))

    def poll(self, *, force: bool = False) -> list:
        self._flush_pending()
        resp, payload = self._call("poll", {"force": bool(force)})
        return wire.decode_events(resp, payload)

    def disconnect_sessions(self, session_ids) -> list:
        self._flush_pending()
        resp, payload = self._call(
            "disconnect", {"sids": list(session_ids)}
        )
        return wire.decode_events(resp, payload)

    def flush(self) -> list:
        self._flush_pending()
        resp, payload = self._call("flush")
        return wire.decode_events(resp, payload)

    def watermark(self, session_id) -> int:
        resp, _ = self._call("watermark", {"sid": session_id})
        return int(resp["r"])

    # ----------------------------------------------------- observation

    def accounting(self) -> dict:
        resp, _ = self._call("accounting")
        return resp["r"]

    def gateway_stats(self) -> dict:
        resp, _ = self._call("gateway_stats")
        return resp["r"]

    def whois(self) -> dict:
        resp, _ = self._call("whois")
        return resp

    # ------------------------------------------------------- lifecycle

    def shutdown(self, *, drain: bool = False) -> None:
        try:
            self._call("shutdown", {"drain": bool(drain)})
        except Exception:
            pass

    def close(self) -> None:
        self._client.close()


# --------------------------------------------------------- entrypoint


def build_parser() -> argparse.ArgumentParser:
    dflt = IngestConfig()
    ap = argparse.ArgumentParser(
        prog="har serve-gateway",
        description=(
            "the fleet's ingest front door (har_tpu.serve.net.gateway) "
            "— one process speaking the journal-frame wire protocol to "
            "clients, multiplexing batched push frames onto already-"
            "running `har serve-worker` processes with header-only edge "
            "admission; prints one JSON ready line {host, port, pid}. "
            "Give two processes the same --ha-root and they form an "
            "elected HA pair: the standby answers {'moved': leader} "
            "and takes the lease over when the leader dies or drains"
        ),
    )
    ap.add_argument("--root", required=True,
                    help="cluster root directory (failover staging)")
    ap.add_argument("--workers-json", required=True,
                    help="JSON list of running workers: "
                         '[{"id", "host", "port", "journal"}, ...]')
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral; the ready line reports it")
    ap.add_argument("--model", default="demo")
    ap.add_argument("--deadline-s", type=float, default=2.0,
                    help="per-RPC deadline toward the workers")
    ap.add_argument("--soft-backlog", type=int, default=dflt.soft_backlog)
    ap.add_argument("--hard-backlog", type=int, default=dflt.hard_backlog)
    ap.add_argument("--max-frame-sessions", type=int,
                    default=dflt.max_frame_sessions)
    ap.add_argument("--max-frame-bytes", type=int,
                    default=dflt.max_frame_bytes)
    ap.add_argument("--max-watermark-lag", type=int,
                    default=dflt.max_watermark_lag)
    ap.add_argument("--tenants", default=None,
                    help='JSON tenant table {"tenant": weight, ...}; '
                         "set = identity enforced at the edge (unknown "
                         "tenant is a protocol violation) and the shed "
                         "ladder runs per tenant on weighted shares")
    ap.add_argument("--ha-root", default=None,
                    help="shared lease directory for an elected gateway "
                         "pair; absent = single-gateway mode")
    ap.add_argument("--lease-s", type=float, default=1.0)
    ap.add_argument("--drain-grace-s", type=float, default=0.25)
    ap.add_argument("--max-idle-s", type=float, default=120.0,
                    help="exit when no RPC arrives for this long "
                         "(orphan protection); 0 disables")
    ap.add_argument("--chaos-point", default=None,
                    help="TESTING: os._exit(137) at the Nth hit of this "
                         "gateway stage boundary — a REAL process kill "
                         "at a chosen kill point")
    ap.add_argument("--chaos-at", type=int, default=1)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from har_tpu.serve.net.client import NetWorker
    from har_tpu.serve.net.controller import NetCluster
    from har_tpu.serve.net.worker import _HardKillPlan, model_pool

    models = model_pool(args.model)
    specs = json.loads(args.workers_json)

    def make_workers():
        return [
            NetWorker(
                spec["id"],
                spec["host"],
                int(spec["port"]),
                spec["journal"],
                deadline_s=args.deadline_s,
            )
            for spec in specs
        ]

    tenants = ()
    if args.tenants:
        tenants = tuple(sorted(json.loads(args.tenants).items()))
    config = IngestConfig(
        soft_backlog=args.soft_backlog,
        hard_backlog=args.hard_backlog,
        max_frame_sessions=args.max_frame_sessions,
        max_frame_bytes=args.max_frame_bytes,
        max_watermark_lag=args.max_watermark_lag,
        tenants=tenants,
    )
    chaos = None
    if args.chaos_point:
        chaos = _HardKillPlan(args.chaos_point, args.chaos_at)
    net_workers: list = []
    if args.ha_root:
        # HA pair: the attachment is built on WINNING the lease —
        # NetCluster.takeover derives placement from actual worker
        # ownership, so a mid-run winner adopts the live sessions the
        # old leader was fronting
        def factory():
            ws = make_workers()
            return NetCluster.takeover(
                models["A"],
                args.root,
                ws,
                loader=lambda ver: models.get(ver, models["A"]),
            )

        gw = IngestGateway(
            cluster_factory=factory,
            config=config,
            host=args.host,
            port=args.port,
            ha_root=args.ha_root,
            lease_s=args.lease_s,
            drain_grace_s=args.drain_grace_s,
            chaos=chaos,
        )
    else:
        net_workers = make_workers()
        # the fleet's geometry is the workers' geometry — ask one
        # instead of trusting a default: the client slices its stream
        # by the hop the gateway advertises, and a mismatch would
        # silently starve (or flood) every window assembler behind the
        # front door
        geo = net_workers[0].geometry()
        cluster = NetCluster(
            models["A"],
            args.root,
            window=int(geo["window"]),
            hop=int(geo["hop"]),
            channels=int(geo["channels"]),
            smoothing=geo["smoothing"],
            loader=lambda ver: models.get(ver, models["A"]),
            _workers=net_workers,
        )
        gw = IngestGateway(
            cluster,
            config=config,
            host=args.host,
            port=args.port,
            chaos=chaos,
        )
    print(
        json.dumps(
            {"host": gw.rpc.host, "port": gw.rpc.port, "pid": os.getpid()}
        ),
        flush=True,
    )
    try:
        return gw.serve_forever(max_idle_s=args.max_idle_s)
    finally:
        for w in net_workers:
            w.close()


def launch_gateway(
    root: str,
    workers,
    *,
    model: str = "demo",
    host: str = "127.0.0.1",
    deadline_s: float = 2.0,
    config: IngestConfig | None = None,
    max_idle_s: float = 120.0,
    ready_timeout_s: float = 30.0,
    ha_root: str | None = None,
    lease_s: float = 1.0,
    drain_grace_s: float = 0.25,
    chaos_point: str | None = None,
    chaos_at: int = 1,
    log_name: str = "gateway.stderr.log",
):
    """Spawn one ``har serve-gateway`` subprocess fronting already-
    running workers (``NetWorker`` proxies from ``launch_workers``) and
    return ``(proc, host, port)`` once its ready line lands.  Stderr is
    captured to ``<root>/<log_name>`` for post-mortems."""
    from har_tpu.serve.net.controller import _read_ready_line

    cfg = config or IngestConfig()
    specs = [
        {
            "id": w.worker_id,
            "host": w.host,
            "port": w.port,
            "journal": w.journal_dir,
        }
        for w in workers
    ]
    os.makedirs(root, exist_ok=True)
    cmd = [
        sys.executable, "-m", "har_tpu.serve.net.gateway",
        "--root", root,
        "--workers-json", json.dumps(specs),
        "--host", host,
        "--model", model,
        "--deadline-s", str(deadline_s),
        "--soft-backlog", str(cfg.soft_backlog),
        "--hard-backlog", str(cfg.hard_backlog),
        "--max-frame-sessions", str(cfg.max_frame_sessions),
        "--max-frame-bytes", str(cfg.max_frame_bytes),
        "--max-watermark-lag", str(cfg.max_watermark_lag),
        "--max-idle-s", str(max_idle_s),
    ]
    if cfg.tenants:
        cmd += ["--tenants", json.dumps(dict(cfg.tenants))]
    if ha_root:
        cmd += [
            "--ha-root", ha_root,
            "--lease-s", str(lease_s),
            "--drain-grace-s", str(drain_grace_s),
        ]
    if chaos_point:
        cmd += ["--chaos-point", chaos_point, "--chaos-at", str(chaos_at)]
    err = open(os.path.join(root, log_name), "wb")
    try:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=err, text=True
        )
    finally:
        err.close()
    try:
        ready = _read_ready_line(
            proc, "gateway", root, ready_timeout_s,
            log_name=log_name,
        )
    except BaseException:
        try:
            proc.kill()
        except OSError:
            pass
        raise
    return proc, ready["host"], ready["port"]


def launch_gateway_pair(
    root: str,
    workers,
    *,
    model: str = "demo",
    host: str = "127.0.0.1",
    deadline_s: float = 2.0,
    config: IngestConfig | None = None,
    lease_s: float = 0.4,
    drain_grace_s: float = 0.25,
    max_idle_s: float = 120.0,
    ready_timeout_s: float = 30.0,
    leader_timeout_s: float = 10.0,
    chaos_point: str | None = None,
    chaos_at: int = 1,
):
    """Spawn an elected gateway PAIR over one shared lease directory
    and return ``[(proc, host, port), (proc, host, port)]`` with the
    FIRST entry holding the lease: gateway A launches alone, the
    launcher waits (via ``whois``) until A is leader, then launches B —
    deterministic initial leadership, so a chaos plan installed on A
    (``chaos_point``/``chaos_at``) kills the ACTIVE gateway."""
    ha_root = os.path.join(root, "gateway-ha")
    os.makedirs(ha_root, exist_ok=True)
    a = launch_gateway(
        root, workers, model=model, host=host, deadline_s=deadline_s,
        config=config, max_idle_s=max_idle_s,
        ready_timeout_s=ready_timeout_s, ha_root=ha_root,
        lease_s=lease_s, drain_grace_s=drain_grace_s,
        chaos_point=chaos_point, chaos_at=chaos_at,
        log_name="gateway-a.stderr.log",
    )
    probe = RpcClient(a[1], a[2], deadline_s=1.0, retries=0)
    try:
        deadline = time.monotonic() + leader_timeout_s
        while True:
            try:
                resp, _ = probe.call("whois")
                if resp.get("role") == "leader":
                    break
            except RpcError:
                pass
            if time.monotonic() > deadline:
                try:
                    a[0].kill()
                except OSError:
                    pass
                raise RuntimeError(
                    "gateway A never took the initial lease"
                )
            time.sleep(0.02)
    finally:
        probe.close()
    try:
        b = launch_gateway(
            root, workers, model=model, host=host, deadline_s=deadline_s,
            config=config, max_idle_s=max_idle_s,
            ready_timeout_s=ready_timeout_s, ha_root=ha_root,
            lease_s=lease_s, drain_grace_s=drain_grace_s,
            log_name="gateway-b.stderr.log",
        )
    except BaseException:
        try:
            a[0].kill()
        except OSError:
            pass
        raise
    return [a, b]


if __name__ == "__main__":  # pragma: no cover - subprocess entrypoint
    sys.exit(main(sys.argv[1:]))
